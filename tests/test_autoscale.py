"""Autoscaling subsystem: decider math (fake clock, zero sleeps), the
collector's gauges, quota-parked scale-ups, and the full
0 -> N -> 0 / scale-from-zero activator loop on a live control plane."""

import json
import threading
import time

import pytest
from conftest import poll_until as wait

from kubeflow_tpu import autoscale
from kubeflow_tpu.api import inferenceservice as api
from kubeflow_tpu.autoscale.decider import Decider, DeciderSpec
from kubeflow_tpu.autoscale.metrics import HeldOverflow, MetricsCollector
from kubeflow_tpu.autoscale.reconciler import ANNO_PREFIX, Autoscaler
from kubeflow_tpu.controllers import workloads
from kubeflow_tpu.controllers.executor import FakeExecutor
from kubeflow_tpu.controllers.inferenceservice import (
    register as register_isvc,
)
from kubeflow_tpu.core import APIServer, Manager, Request
from kubeflow_tpu.core.httpapi import serve
from kubeflow_tpu.core.objects import api_object
from kubeflow_tpu.gateway import Gateway


# -- decider: pure clock-injected math, NO sleeps anywhere -------------------

def test_decider_stable_scale_up_and_clamp():
    spec = DeciderSpec(target=2.0, stable_window=10.0, panic_window=1.0,
                       panic_threshold=100.0,  # panic out of the way
                       min_scale=1, max_scale=4)
    d = Decider(spec)
    for t in range(10):
        d.record(float(t), 6.0)
    out = d.desired(9.0, ready=3)
    assert out.desired == 3          # ceil(6 / 2)
    assert not out.panic
    # clamping: a flood beyond max_scale pins at max
    for t in range(10, 20):
        d.record(float(t), 40.0)
    assert d.desired(19.0, ready=3).desired == 4
    # and silence never drops below min_scale
    d2 = Decider(spec)
    d2.record(0.0, 0.0)
    assert d2.desired(0.0, ready=1).desired == 1


def test_decider_panic_window_reacts_to_burst():
    """A burst inside the short panic window must scale up immediately
    even though the stable-window average barely moved — and panic must
    hold its high-water mark (no scale-down mid-panic)."""
    spec = DeciderSpec(target=1.0, stable_window=60.0, panic_window=6.0,
                       panic_threshold=2.0, min_scale=0, max_scale=100)
    d = Decider(spec)
    for t in range(54):              # nearly a stable window of quiet
        d.record(float(t), 0.0)
    for t in range(54, 60):          # 6s burst of 8 concurrent
        d.record(float(t), 8.0)
    out = d.desired(60.0, ready=1)   # panic window covers just the burst
    assert out.panic
    assert out.desired == 8          # panic window average, not stable
    # burst gone: panic holds the high-water mark until a stable window
    # passes with no re-trigger
    for t in range(60, 90):
        d.record(float(t), 0.0)
    held = d.desired(89.0, ready=8)
    assert held.panic and held.desired == 8
    for t in range(90, 125):
        d.record(float(t), 0.0)
    calm = d.desired(124.0, ready=8)
    assert not calm.panic
    assert calm.desired == 0         # stable window is quiet -> to zero


def test_decider_scale_down_delay():
    """Raw desired falls as load stops, but the applied desired is the
    trailing max over scale_down_delay — then drops to zero."""
    spec = DeciderSpec(target=1.0, stable_window=2.0, panic_window=0.5,
                       panic_threshold=100.0, scale_down_delay=5.0,
                       min_scale=0, max_scale=10)
    d = Decider(spec)
    desired_at = {}
    for t in range(11):
        d.record(float(t), 4.0 if t <= 2 else 0.0)
        desired_at[t] = d.desired(float(t), ready=4).desired
    assert desired_at[2] == 4
    assert desired_at[7] == 4        # raw is 0 by t=5; delay holds 4
    assert desired_at[10] == 0       # delay window drained -> scale down


def test_decider_scale_to_zero_and_back():
    spec = DeciderSpec(target=2.0, stable_window=4.0, panic_window=1.0,
                       min_scale=0, max_scale=5)
    d = Decider(spec)
    for t in range(8):
        d.record(float(t), 0.0)
    assert d.desired(7.0, ready=1).desired == 0
    # demand arriving at zero replicas (the activator's held request)
    d.record(8.0, 1.0)
    out = d.desired(8.0, ready=0)
    assert out.desired >= 1


# -- collector ---------------------------------------------------------------

def test_collector_gauges_and_bounded_hold():
    c = MetricsCollector()
    key = ("ns", "svc")
    c.inc(key)
    c.inc(key)
    c.dec(key)
    assert c.concurrency(key) == 1.0
    with c.hold(key, limit=2):
        assert c.concurrency(key) == 2.0
        assert c.queue_depth(key) == 1
        with c.hold(key, limit=2):
            with pytest.raises(HeldOverflow):
                c.hold(key, limit=2)
    assert c.queue_depth(key) == 0
    # engine stats fold into the same gauge (serving/engine.py stats())
    c.add_source(key, lambda: {"active": 3, "queued": 2})
    assert c.concurrency(key) == 6.0
    c.remove_source(key)
    c.dec(key)
    assert c.concurrency(key) == 0.0


def test_engine_stats_snapshot():
    from kubeflow_tpu.serving.predictor import GenerativePredictor

    p = GenerativePredictor("llama", size="tiny", max_batch=2, max_seq=32)
    stats = p.engine.stats()
    # the autoscaler's keys plus the paged-KV standing (ISSUE 11)
    assert {k: stats[k] for k in ("active", "queued", "max_batch")} \
        == {"active": 0, "queued": 0, "max_batch": 2}
    assert stats["kv_pool"]["orphan_pages"] == 0
    p.generate([[1, 2]], max_new_tokens=2)
    assert p.engine.stats()["active"] == 0  # drained after sync generate


# -- reconciler: deterministic, driven by direct reconcile calls -------------

def _annotated_isvc(name="m", ns="serving", **annos):
    isvc = api.new(name, ns, topology="v5e-4")
    defaults = {"target": "2", "minReplicas": "0", "maxReplicas": "5",
                "window": "10", "panicWindow": "1", "tick": "0.05"}
    defaults.update({k: str(v) for k, v in annos.items()})
    isvc["metadata"]["annotations"] = {
        ANNO_PREFIX + k: v for k, v in defaults.items()}
    return isvc


def test_reconciler_patches_replicas_from_samples():
    """No manager, no sleeps: feed the collector, step a fake clock, and
    watch spec.replicas change through the store."""
    server = APIServer()
    collector = autoscale.get_collector(server)
    now = [0.0]
    scaler = Autoscaler(server, collector, clock=lambda: now[0])
    server.create(_annotated_isvc())
    server.create(api_object("Deployment", "m", "serving",
                             spec={"replicas": 0, "template": {}}))
    req = Request("serving", "m")

    for _ in range(6):               # sustained concurrency of 6
        collector.inc(("serving", "m"))
    for _ in range(20):
        now[0] += 0.5
        scaler.reconcile(req)
    dep = server.get("Deployment", "m", "serving")
    assert dep["spec"]["replicas"] == 3   # ceil(6 / target 2)
    isvc = server.get(api.KIND, "m", "serving")
    state = isvc["status"]["autoscaler"]
    assert state["appliedReplicas"] == 3
    assert state["parked"] == 0
    assert state["stableConcurrency"] > 0

    for _ in range(6):
        collector.dec(("serving", "m"))
    for _ in range(30):              # drain a full stable window
        now[0] += 0.5
        scaler.reconcile(req)
    assert server.get("Deployment", "m", "serving")["spec"]["replicas"] == 0


def test_reconciler_ignores_unannotated_isvc():
    server = APIServer()
    scaler = Autoscaler(server, autoscale.get_collector(server),
                        clock=lambda: 0.0)
    server.create(api.new("plain", "serving"))
    server.create(api_object("Deployment", "plain", "serving",
                             spec={"replicas": 1, "template": {}}))
    assert scaler.reconcile(Request("serving", "plain")) is None
    assert server.get("Deployment", "plain",
                      "serving")["spec"]["replicas"] == 1


# -- drain-aware scale-down (ISSUE 6) ----------------------------------------

def _two_pod_deployment(server, ports=(9001, 9002)):
    server.create(api_object("Deployment", "m", "serving",
                             spec={"replicas": 2, "template": {}}))
    server.patch_status("Deployment", "m", "serving",
                        {"replicas": 2, "readyReplicas": 2})
    for i, port in enumerate(ports):
        pod = api_object("Pod", f"m-{i}", "serving",
                         labels={"isvc": "m"},
                         spec={"containers": [{"name": "c"}]})
        server.create(pod)
        server.patch_status("Pod", f"m-{i}", "serving", {
            "phase": "Running", "podIP": "127.0.0.1",
            "portMap": {"8602": port}})


def _drive(scaler, req, now, ticks, step=0.5):
    for _ in range(ticks):
        now[0] += step
        scaler.reconcile(req)


def test_scale_down_drains_victim_before_replicas_patch():
    """The acceptance flow: the victim pod (highest ordinal — exactly the
    one the Deployment controller deletes) is marked draining via the
    gateway BEFORE any replicas patch, the patch waits while the victim
    still carries a live stream, and lands the tick after quiesce."""
    from kubeflow_tpu import gateway as gw

    server = APIServer()
    collector = autoscale.get_collector(server)
    now = [0.0]
    scaler = Autoscaler(server, collector, clock=lambda: now[0])
    server.create(_annotated_isvc(target="2", minReplicas="1", window="2",
                                  panicThreshold="100",
                                  drainGrace="600"))
    _two_pod_deployment(server)
    req = Request("serving", "m")

    for _ in range(4):                   # sustained 4 -> desired 2
        collector.inc(("serving", "m"))
    _drive(scaler, req, now, 10)
    assert server.get("Deployment", "m",
                      "serving")["spec"]["replicas"] == 2

    # load drops to 1 (-> desired 1) while the victim pod m-1 still
    # carries one live proxied stream
    for _ in range(3):
        collector.dec(("serving", "m"))
    collector.inc_backend(("127.0.0.1", 9002))
    _drive(scaler, req, now, 12)
    assert gw.pod_draining(server.get("Pod", "m-1", "serving"))
    assert not gw.pod_draining(server.get("Pod", "m-0", "serving"))
    # the patch is DEFERRED: replicas still 2 while the stream lives
    assert server.get("Deployment", "m",
                      "serving")["spec"]["replicas"] == 2
    state = server.get(api.KIND, "m", "serving")["status"]["autoscaler"]
    assert state["draining"] == 1

    # the stream finishes -> the very next tick patches replicas down
    collector.dec_backend(("127.0.0.1", 9002))
    _drive(scaler, req, now, 2)
    assert server.get("Deployment", "m",
                      "serving")["spec"]["replicas"] == 1
    state = server.get(api.KIND, "m", "serving")["status"]["autoscaler"]
    assert state["draining"] == 0


def test_shallower_redecision_undrains_ex_victims_only():
    """A pending 3->1 scale-down re-decided to 3->2 shrinks the victim
    range: m-1 (no longer a victim) must return to rotation immediately,
    while m-2 stays draining until its streams quiesce — a stale
    draining mark on a surviving replica is a permanent capacity
    blackhole."""
    from kubeflow_tpu import gateway as gw

    server = APIServer()
    collector = autoscale.get_collector(server)
    now = [0.0]
    scaler = Autoscaler(server, collector, clock=lambda: now[0])
    server.create(_annotated_isvc(target="2", minReplicas="1", window="2",
                                  panicThreshold="100",
                                  drainGrace="600"))
    server.create(api_object("Deployment", "m", "serving",
                             spec={"replicas": 3, "template": {}}))
    server.patch_status("Deployment", "m", "serving",
                        {"replicas": 3, "readyReplicas": 3})
    for i, port in enumerate((9001, 9002, 9003)):
        server.create(api_object("Pod", f"m-{i}", "serving",
                                 labels={"isvc": "m"},
                                 spec={"containers": [{"name": "c"}]}))
        server.patch_status("Pod", f"m-{i}", "serving", {
            "phase": "Running", "podIP": "127.0.0.1",
            "portMap": {"8602": port}})
    req = Request("serving", "m")

    for _ in range(6):                   # sustained 6 -> desired 3
        collector.inc(("serving", "m"))
    _drive(scaler, req, now, 10)
    # load drops to 1 -> desired 1; BOTH victims carry live streams, so
    # the patch defers and m-1 + m-2 are both draining
    for _ in range(5):
        collector.dec(("serving", "m"))
    collector.inc_backend(("127.0.0.1", 9002))
    collector.inc_backend(("127.0.0.1", 9003))
    _drive(scaler, req, now, 12)
    assert gw.pod_draining(server.get("Pod", "m-1", "serving"))
    assert gw.pod_draining(server.get("Pod", "m-2", "serving"))
    assert server.get("Deployment", "m",
                      "serving")["spec"]["replicas"] == 3

    # load rises to 3 -> desired 2: m-1 leaves the victim range and must
    # be undrained even though its stream still lives; m-2 keeps draining
    for _ in range(2):
        collector.inc(("serving", "m"))
    _drive(scaler, req, now, 12)
    assert not gw.pod_draining(server.get("Pod", "m-1", "serving"))
    assert gw.pod_draining(server.get("Pod", "m-2", "serving"))
    assert server.get("Deployment", "m",
                      "serving")["spec"]["replicas"] == 3

    # m-2 quiesces -> the patch lands at 2, m-1 still routable
    collector.dec_backend(("127.0.0.1", 9003))
    _drive(scaler, req, now, 3)
    assert server.get("Deployment", "m",
                      "serving")["spec"]["replicas"] == 2
    assert not gw.pod_draining(server.get("Pod", "m-1", "serving"))
    state = server.get(api.KIND, "m", "serving")["status"]["autoscaler"]
    assert state["draining"] == 0
    collector.dec_backend(("127.0.0.1", 9002))


def test_drain_state_is_per_service_not_name_prefix():
    """Service "m" must not claim (or undrain) the drain state of a
    sibling service "m-foo": victim keys match the exact {name}-{ordinal}
    pattern, not a name prefix."""
    server = APIServer()
    collector = autoscale.get_collector(server)
    now = [0.0]
    scaler = Autoscaler(server, collector, clock=lambda: now[0])
    scaler._drain_started[("serving", "m-foo-1")] = 0.0
    assert scaler._drain_keys(Request("serving", "m")) == []
    assert scaler._drain_keys(Request("serving", "m-foo")) == [
        ("serving", "m-foo-1")]


def test_scale_down_drain_grace_expiry_forces_patch():
    """A wedged stream must not park the scale-down forever: once
    drainGrace expires the replicas patch proceeds regardless."""
    server = APIServer()
    collector = autoscale.get_collector(server)
    now = [0.0]
    scaler = Autoscaler(server, collector, clock=lambda: now[0])
    server.create(_annotated_isvc(target="2", minReplicas="1", window="2",
                                  panicThreshold="100",
                                  drainGrace="1.5"))
    _two_pod_deployment(server)
    req = Request("serving", "m")
    for _ in range(4):
        collector.inc(("serving", "m"))
    _drive(scaler, req, now, 10)
    for _ in range(3):
        collector.dec(("serving", "m"))
    collector.inc_backend(("127.0.0.1", 9002))   # wedged forever
    _drive(scaler, req, now, 12)                  # > grace worth of ticks
    assert server.get("Deployment", "m",
                      "serving")["spec"]["replicas"] == 1
    collector.dec_backend(("127.0.0.1", 9002))


def test_scale_up_mid_drain_returns_victim_to_rotation():
    """A pending scale-down re-decided upward must UNDRAIN the victim —
    capacity the decider wants back goes back in rotation."""
    from kubeflow_tpu import gateway as gw

    server = APIServer()
    collector = autoscale.get_collector(server)
    now = [0.0]
    scaler = Autoscaler(server, collector, clock=lambda: now[0])
    server.create(_annotated_isvc(target="2", minReplicas="1", window="2",
                                  panicThreshold="100",
                                  drainGrace="600"))
    _two_pod_deployment(server)
    req = Request("serving", "m")
    for _ in range(4):
        collector.inc(("serving", "m"))
    _drive(scaler, req, now, 10)
    for _ in range(3):
        collector.dec(("serving", "m"))
    collector.inc_backend(("127.0.0.1", 9002))
    _drive(scaler, req, now, 12)
    assert gw.pod_draining(server.get("Pod", "m-1", "serving"))

    for _ in range(3):                   # the burst returns -> desired 2
        collector.inc(("serving", "m"))
    _drive(scaler, req, now, 12)
    assert not gw.pod_draining(server.get("Pod", "m-1", "serving"))
    assert server.get("Deployment", "m",
                      "serving")["spec"]["replicas"] == 2
    collector.dec_backend(("127.0.0.1", 9002))
    for _ in range(4):
        collector.dec(("serving", "m"))


# -- quota parking: a scale-up past TPU quota parks, never flaps -------------

def test_scale_up_beyond_quota_parks_without_flapping():
    from kubeflow_tpu.core import quota as quota_mod

    server = APIServer()
    quota_mod.register(server)
    mgr = Manager(server)
    register_isvc(server, mgr)
    workloads.register(server, mgr)
    mgr.add(FakeExecutor(server, complete=False))
    collector = autoscale.get_collector(server)
    now = [0.0]
    scaler = Autoscaler(server, collector, clock=lambda: now[0])
    mgr.start()
    try:
        # room for exactly 2 predictor pods (4 chips each)
        server.create(api_object("ResourceQuota", quota_mod.QUOTA_NAME,
                                 "serving", spec={"hard": {
                                     "cloud-tpu.google.com/v5e": 8}}))
        server.create(_annotated_isvc(target="1", initialScale="1"))
        wait(lambda: _pods_running(server, "serving", 1))

        for _ in range(3):           # demand wants 3 pods; quota fits 2
            collector.inc(("serving", "m"))
        req = Request("serving", "m")
        for _ in range(6):
            now[0] += 0.5
            scaler.reconcile(req)
        wait(lambda: _pods_running(server, "serving", 2))
        history = []
        for _ in range(10):          # stability: no flapping at the cap
            now[0] += 0.5
            scaler.reconcile(req)
            history.append(server.get("Deployment", "m",
                                      "serving")["spec"]["replicas"])
        assert history == [2] * 10
        state = server.get(api.KIND, "m", "serving")["status"]["autoscaler"]
        assert state["desiredReplicas"] == 3
        assert state["appliedReplicas"] == 2
        assert state["parked"] == 1
    finally:
        mgr.stop()


def _pods_running(server, ns, n):
    pods = [p for p in server.list("Pod", namespace=ns)
            if p.get("status", {}).get("phase") == "Running"]
    return True if len(pods) >= n else None


# -- e2e: 0 -> N -> 0 through the gateway, activator answers at zero ---------

def _backend_app(environ, start_response):
    time.sleep(0.15)                 # hold concurrency open under load
    payload = json.dumps({"ok": True,
                          "path": environ.get("PATH_INFO")}).encode()
    start_response("200 OK", [("Content-Type", "application/json"),
                              ("Content-Length", str(len(payload)))])
    return [payload]


def _wsgi_get(app, path):
    """Drive a WSGI callable directly (no sockets on the front side)."""
    from io import BytesIO

    status_box = {}

    def start_response(status, headers):
        status_box["status"] = status

    environ = {"REQUEST_METHOD": "GET", "PATH_INFO": path,
               "QUERY_STRING": "", "wsgi.input": BytesIO(b""),
               "wsgi.url_scheme": "http"}
    body = b"".join(app(environ, start_response))
    return int(status_box["status"].split()[0]), body


@pytest.fixture()
def serving_stack():
    stub, _ = serve(_backend_app, 0)          # the "predictor" pod process
    stub_port = stub.server_address[1]
    server = APIServer()
    mgr = Manager(server)
    register_isvc(server, mgr)
    workloads.register(server, mgr)
    autoscale.register(server, mgr)
    mgr.add(FakeExecutor(server, complete=False,
                         portmap={str(api.PORT): stub_port}))
    gateway = Gateway(server, connect_retries=8, retry_delay=0.05)
    assert gateway.activator is not None      # auto-wired from autoscale
    mgr.start()
    yield server, mgr, gateway
    mgr.stop()
    stub.shutdown()


def test_scale_from_zero_to_n_to_zero(serving_stack):
    """The acceptance loop: a request at zero replicas is held and
    answered 200 after activator-driven scale-up; sustained load scales
    to N; the idle window scales back to zero — all observed through the
    store as patches to the Deployment's spec.replicas."""
    server, mgr, gateway = serving_stack
    server.create(_annotated_isvc(
        target="2", minReplicas="0", maxReplicas="4", initialScale="0",
        window="1.2", panicWindow="0.3", scaleDownDelay="0.2",
        tick="0.05"))
    wait(lambda: _exists(server, "VirtualService", "isvc-m", "serving"))
    assert server.get("Deployment", "m",
                      "serving")["spec"]["replicas"] == 0

    # a request arriving at ZERO replicas: held, scaled 0->1, answered
    code, body = _wsgi_get(gateway, "/serving/serving/m/v1/models")
    assert code == 200
    assert json.loads(body)["ok"] is True
    assert server.get("Deployment", "m",
                      "serving")["spec"]["replicas"] >= 1

    # sustained concurrency ~6 against target 2 -> replicas climb past 1
    stop = threading.Event()

    def pound():
        while not stop.is_set():
            _wsgi_get(gateway, "/serving/serving/m/v1/models")

    threads = [threading.Thread(target=pound, daemon=True)
               for _ in range(6)]
    for t in threads:
        t.start()
    try:
        wait(lambda: (server.get("Deployment", "m", "serving")
                      ["spec"]["replicas"] >= 2) or None, timeout=15)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    replicas = server.get("Deployment", "m", "serving")["spec"]["replicas"]
    assert 2 <= replicas <= 4

    # idle: stable window drains -> back to zero, pods deleted
    wait(lambda: (server.get("Deployment", "m", "serving")
                  ["spec"]["replicas"] == 0) or None, timeout=20)
    wait(lambda: None if server.list("Pod", namespace="serving") else True,
         timeout=10)
    state = server.get(api.KIND, "m", "serving")["status"]["autoscaler"]
    assert state["desiredReplicas"] == 0

    # and the dashboard metrics service surfaces the same state
    from kubeflow_tpu.dashboard.metrics_service import LocalMetricsService

    rows = LocalMetricsService(server).get_autoscaler_state()
    assert any(r["name"] == "m" and r["namespace"] == "serving"
               for r in rows)


def test_activator_not_engaged_for_plain_isvc(serving_stack):
    """Without autoscaling annotations a dead backend stays a plain 503 —
    the activator must not hold requests it cannot un-zero."""
    server, mgr, gateway = serving_stack
    isvc = api.new("fixed", "serving", min_replicas=0)
    server.create(isvc)
    wait(lambda: _exists(server, "VirtualService", "isvc-fixed", "serving"))
    code, _ = _wsgi_get(gateway, "/serving/serving/fixed/v1/models")
    assert code == 503


def _exists(server, kind, name, ns):
    from kubeflow_tpu.core.store import NotFound

    try:
        server.get(kind, name, ns)
        return True
    except NotFound:
        return None
