"""Config / metrics / logging unit tests."""

import json

from kubeflow_tpu.utils.config import Config, config_field
from kubeflow_tpu.utils.metrics import Registry


class CullerConfig(Config):
    enable_culling: bool = config_field(False, env="ENABLE_CULLING")
    idle_time_min: int = config_field(1440, env="IDLE_TIME")
    name: str = config_field("nb", read_only=True)


def test_config_defaults():
    cfg = CullerConfig()
    assert cfg.enable_culling is False and cfg.idle_time_min == 1440


def test_config_env_layer():
    cfg = CullerConfig.load(env={"ENABLE_CULLING": "true", "IDLE_TIME": "30"})
    assert cfg.enable_culling is True and cfg.idle_time_min == 30


def test_config_flag_beats_env():
    cfg = CullerConfig.load(argv=["--idle-time-min", "5"],
                            env={"IDLE_TIME": "30"})
    assert cfg.idle_time_min == 5


def test_config_file_layer(tmp_path):
    f = tmp_path / "c.json"
    f.write_text(json.dumps({"idle_time_min": 99, "name": "pinned"}))
    cfg = CullerConfig.load(config_file=str(f), env={})
    assert cfg.idle_time_min == 99
    # read_only: file value wins over explicit override (spawner semantics)
    cfg2 = CullerConfig.load(config_file=str(f), env={}, name="user-pick")
    assert cfg2.name == "pinned"


def test_config_read_only_without_file_value():
    # read_only only pins when the value came from the config FILE
    cfg = CullerConfig.load(env={}, name="user-pick")
    assert cfg.name == "user-pick"


def test_metrics_exposition():
    reg = Registry()
    c = reg.counter("requests_total", "reqs", labels=("code",))
    c.labels("200").inc()
    c.labels("200").inc()
    c.labels("500").inc()
    g = reg.gauge("up", "liveness")
    g.set(1)
    text = reg.expose()
    assert 'requests_total{code="200"} 2.0' in text
    assert 'requests_total{code="500"} 1.0' in text
    assert "# TYPE up gauge" in text
    assert c.get("200") == 2.0


def test_gauge_set_function_fresh_on_every_read():
    # regression: _collect_fn gauges used to refresh only inside
    # Registry.expose(), so get()/total() (dashboard + loadtest paths)
    # read whatever the LAST exposition happened to cache
    reg = Registry()
    g = reg.gauge("depth", "live queue depth")
    box = {"v": 1.0}
    g.set_function(lambda: box["v"])
    assert g.get() == 1.0
    box["v"] = 42.0
    assert g.get() == 42.0          # no expose() in between
    assert g.total() == 42.0
    box["v"] = 7.0
    assert "depth 7.0" in reg.expose()


def test_histogram_reads_locked_against_inplace_mutation():
    # regression: count()/sum()/get() used to read the row with NO lock
    # while _observe mutates it in place (bucket bumped, sum not yet —
    # a torn pair).  The fix makes every read snapshot under self._lock;
    # prove it by holding the lock and watching each read block.
    import threading

    reg = Registry()
    h = reg.histogram("work_seconds", "x", buckets=(0.5, 2.0))
    h.observe(1.0)
    for read in (h.count, h.sum, h.get):
        h._lock.acquire()
        out = []
        t = threading.Thread(target=lambda r=read: out.append(r()),
                             daemon=True)
        t.start()
        t.join(timeout=0.2)
        assert t.is_alive(), f"{read.__name__} read without the lock"
        h._lock.release()
        t.join(timeout=5)
        assert out == [1.0]
    # and the concurrent smoke: totals exact after racing observers
    def writer():
        for _ in range(4000):
            h.observe(1.0)

    stop = threading.Event()
    seen = []

    def reader():
        while not stop.is_set():
            seen.append((h.count(), h.sum()))

    threads = [threading.Thread(target=writer) for _ in range(4)]
    r = threading.Thread(target=reader)
    r.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    r.join()
    assert h.count() == 16001 and h.sum() == 16001.0
    assert h.get() == h.count()     # histogram scalar reading = count


def test_histogram_percentile_edge_cases():
    reg = Registry()
    h = reg.histogram("lat_seconds", "x", buckets=(0.1, 1.0))
    # empty: no observations at all
    assert h.percentile(99) == 0.0
    # all-+Inf: every observation above the largest finite bound clamps
    # to that bound
    h.observe(50.0)
    h.observe(99.0)
    assert h.percentile(50) == 1.0
    assert h.percentile(99) == 1.0
    # single finite bucket
    h2 = reg.histogram("one_seconds", "x", buckets=(1.0,))
    h2.observe(0.5)
    assert 0.0 < h2.percentile(50) <= 1.0
    # q=0 and q=100 stay within the value domain
    assert h2.percentile(0) == 0.0
    assert h2.percentile(100) <= 1.0


def test_histogram_exemplar_reservoir_bounded_and_tail_addressable():
    from kubeflow_tpu.utils.metrics import Histogram

    reg = Registry()
    h = reg.histogram("lat_seconds", "x", buckets=(0.1, 1.0))
    for i in range(10):
        h.observe(0.05, exemplar=f"fast{i}")
    h.observe(5.0, exemplar="slow0")
    h.observe(0.5)                      # no exemplar: reservoir untouched
    ex = h.exemplars()
    # bounded: the fast bucket kept only the newest K
    assert [e["ref"] for e in ex[0.1]] == [
        f"fast{i}" for i in range(10 - Histogram.EXEMPLARS_PER_BUCKET, 10)]
    # the tail (+Inf) bucket addresses the slow trace
    assert [e["ref"] for e in ex[float("inf")]] == ["slow0"]
    assert 1.0 not in ex                # nothing ever attached there
    # labeled histograms keep reservoirs per label set
    hl = reg.histogram("lab_seconds", "x", labels=("op",),
                       buckets=(0.1, 1.0))
    hl.labels("read").observe(0.02, exemplar="r1")
    assert [e["ref"] for e in hl.exemplars("read")[0.1]] == ["r1"]
    assert hl.exemplars("write") == {}


def test_exposition_golden_file_and_parser_round_trip():
    """The obs scraper parses Registry.expose() text; this golden file
    pins the format so the two cannot drift apart silently.  If the
    exposition format changes ON PURPOSE, regenerate the golden (see the
    test body) and fix obs.parse_exposition in the same commit."""
    import pathlib

    from kubeflow_tpu.obs import parse_exposition

    reg = Registry()
    c = reg.counter("requests_total", "reqs by code", labels=("code",))
    c.labels("200").inc(3)
    c.labels("503").inc()
    g = reg.gauge("depth", "queue depth")
    g.set(2.5)
    gf = reg.gauge("fn_depth", "function-backed")
    gf.set_function(lambda: 4.0)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(9.0)
    hl = reg.histogram("op_seconds", "per-op latency", labels=("op",),
                       buckets=(1.0,))
    hl.labels("read").observe(0.5)
    text = reg.expose()
    golden = pathlib.Path(__file__).parent / "golden" / \
        "metrics_exposition.txt"
    # regenerate: golden.write_text(text)
    assert text == golden.read_text()
    # round trip: the parser recovers every series with its TYPE
    samples = {(s.name, s.labels): (s.value, s.kind)
               for s in parse_exposition(text)}
    assert samples[("requests_total", (("code", "200"),))] == (3.0,
                                                               "counter")
    assert samples[("depth", ())] == (2.5, "gauge")
    assert samples[("fn_depth", ())] == (4.0, "gauge")
    assert samples[("lat_seconds_count", ())] == (3.0, "histogram")
    assert samples[("lat_seconds_sum", ())] == (9.55, "histogram")
    assert samples[("lat_seconds_bucket", (("le", "+Inf"),))][0] == 3.0
    assert samples[("op_seconds_bucket",
                    (("le", "1.0"), ("op", "read")))] == (1.0, "histogram")


class _FakeProfiler:
    """Counts start/stop calls — the injectable backend that makes the
    window guard testable without jax."""

    def __init__(self):
        self.starts = 0
        self.stops = 0

    def start_trace(self, directory):
        self.starts += 1

    def stop_trace(self):
        self.stops += 1


def test_step_window_tracer_captures_one_window(tmp_path):
    from kubeflow_tpu.utils.profiler import StepWindowTracer

    prof = _FakeProfiler()
    t = StepWindowTracer(str(tmp_path), start_step=3, num_steps=2,
                         backend=prof)
    for step in range(6):
        t.on_step(step)
    t.close()
    assert (prof.starts, prof.stops) == (1, 1)


def test_step_window_tracer_replayed_start_step_never_double_starts(
        tmp_path):
    """Checkpoint-resume replays step numbers: after the window is
    written, seeing ``start_step`` again must NOT call start_trace a
    second time (a second live trace raises inside the runtime)."""
    from kubeflow_tpu.utils.profiler import StepWindowTracer

    prof = _FakeProfiler()
    t = StepWindowTracer(str(tmp_path), start_step=2, num_steps=2,
                         backend=prof)
    for step in (2, 3, 4):        # window captured: steps 2..3
        t.on_step(step)
    assert (prof.starts, prof.stops) == (1, 1)
    for step in (2, 3, 4, 5):     # resume replays the window start
        t.on_step(step)
    t.close()
    assert (prof.starts, prof.stops) == (1, 1)


def test_step_window_tracer_repeated_start_step_single_start(tmp_path):
    """The same step number arriving twice while the window is OPEN
    (retried step after preemption) starts exactly one trace."""
    from kubeflow_tpu.utils.profiler import StepWindowTracer

    prof = _FakeProfiler()
    t = StepWindowTracer(str(tmp_path), start_step=1, num_steps=3,
                         backend=prof)
    for step in (1, 1, 2):
        t.on_step(step)
    assert prof.starts == 1
    t.close()
    assert prof.stops == 1


def test_step_window_tracer_noop_without_directory():
    from kubeflow_tpu.utils.profiler import StepWindowTracer

    prof = _FakeProfiler()
    t = StepWindowTracer(None, start_step=0, num_steps=2, backend=prof)
    for step in range(4):
        t.on_step(step)
    t.close()
    assert (prof.starts, prof.stops) == (0, 0)
