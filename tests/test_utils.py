"""Config / metrics / logging unit tests."""

import json

from kubeflow_tpu.utils.config import Config, config_field
from kubeflow_tpu.utils.metrics import Registry


class CullerConfig(Config):
    enable_culling: bool = config_field(False, env="ENABLE_CULLING")
    idle_time_min: int = config_field(1440, env="IDLE_TIME")
    name: str = config_field("nb", read_only=True)


def test_config_defaults():
    cfg = CullerConfig()
    assert cfg.enable_culling is False and cfg.idle_time_min == 1440


def test_config_env_layer():
    cfg = CullerConfig.load(env={"ENABLE_CULLING": "true", "IDLE_TIME": "30"})
    assert cfg.enable_culling is True and cfg.idle_time_min == 30


def test_config_flag_beats_env():
    cfg = CullerConfig.load(argv=["--idle-time-min", "5"],
                            env={"IDLE_TIME": "30"})
    assert cfg.idle_time_min == 5


def test_config_file_layer(tmp_path):
    f = tmp_path / "c.json"
    f.write_text(json.dumps({"idle_time_min": 99, "name": "pinned"}))
    cfg = CullerConfig.load(config_file=str(f), env={})
    assert cfg.idle_time_min == 99
    # read_only: file value wins over explicit override (spawner semantics)
    cfg2 = CullerConfig.load(config_file=str(f), env={}, name="user-pick")
    assert cfg2.name == "pinned"


def test_config_read_only_without_file_value():
    # read_only only pins when the value came from the config FILE
    cfg = CullerConfig.load(env={}, name="user-pick")
    assert cfg.name == "user-pick"


def test_metrics_exposition():
    reg = Registry()
    c = reg.counter("requests_total", "reqs", labels=("code",))
    c.labels("200").inc()
    c.labels("200").inc()
    c.labels("500").inc()
    g = reg.gauge("up", "liveness")
    g.set(1)
    text = reg.expose()
    assert 'requests_total{code="200"} 2.0' in text
    assert 'requests_total{code="500"} 1.0' in text
    assert "# TYPE up gauge" in text
    assert c.get("200") == 2.0


class _FakeProfiler:
    """Counts start/stop calls — the injectable backend that makes the
    window guard testable without jax."""

    def __init__(self):
        self.starts = 0
        self.stops = 0

    def start_trace(self, directory):
        self.starts += 1

    def stop_trace(self):
        self.stops += 1


def test_step_window_tracer_captures_one_window(tmp_path):
    from kubeflow_tpu.utils.profiler import StepWindowTracer

    prof = _FakeProfiler()
    t = StepWindowTracer(str(tmp_path), start_step=3, num_steps=2,
                         backend=prof)
    for step in range(6):
        t.on_step(step)
    t.close()
    assert (prof.starts, prof.stops) == (1, 1)


def test_step_window_tracer_replayed_start_step_never_double_starts(
        tmp_path):
    """Checkpoint-resume replays step numbers: after the window is
    written, seeing ``start_step`` again must NOT call start_trace a
    second time (a second live trace raises inside the runtime)."""
    from kubeflow_tpu.utils.profiler import StepWindowTracer

    prof = _FakeProfiler()
    t = StepWindowTracer(str(tmp_path), start_step=2, num_steps=2,
                         backend=prof)
    for step in (2, 3, 4):        # window captured: steps 2..3
        t.on_step(step)
    assert (prof.starts, prof.stops) == (1, 1)
    for step in (2, 3, 4, 5):     # resume replays the window start
        t.on_step(step)
    t.close()
    assert (prof.starts, prof.stops) == (1, 1)


def test_step_window_tracer_repeated_start_step_single_start(tmp_path):
    """The same step number arriving twice while the window is OPEN
    (retried step after preemption) starts exactly one trace."""
    from kubeflow_tpu.utils.profiler import StepWindowTracer

    prof = _FakeProfiler()
    t = StepWindowTracer(str(tmp_path), start_step=1, num_steps=3,
                         backend=prof)
    for step in (1, 1, 2):
        t.on_step(step)
    assert prof.starts == 1
    t.close()
    assert prof.stops == 1


def test_step_window_tracer_noop_without_directory():
    from kubeflow_tpu.utils.profiler import StepWindowTracer

    prof = _FakeProfiler()
    t = StepWindowTracer(None, start_step=0, num_steps=2, backend=prof)
    for step in range(4):
        t.on_step(step)
    t.close()
    assert (prof.starts, prof.stops) == (0, 0)
