"""Prefix-aware KV cache reuse (ISSUE 3): radix-tree longest-prefix match,
LRU eviction under an HBM budget with refcounted in-flight holds, and the
engine's warm admission path — whose outputs must be TOKEN-IDENTICAL to
the cold path for the same (prompt, seed, sampling params)."""

import jax.numpy as jnp
import pytest

from kubeflow_tpu.serving.prefix_cache import PrefixCache, block_nbytes


def blk(snap: int = 16):
    """A stand-in KV block shaped like the engine's ([1, snap, H, D])."""
    return {"layers": [{"k": jnp.zeros((1, snap, 1, 2), jnp.float32),
                        "v": jnp.zeros((1, snap, 1, 2), jnp.float32)}]}


BLK_BYTES = block_nbytes(blk())


# -- radix tree unit tests -----------------------------------------------------
def test_longest_prefix_match_with_edge_splits():
    pc = PrefixCache(1 << 30)
    assert pc.insert((1, 2, 3, 4), blk())
    assert pc.insert((1, 2, 5, 6), blk())   # splits the (1,2,3,4) edge

    node, usable = pc.match((1, 2, 3, 4))
    assert usable == 4 and node.block is not None
    _, usable = pc.match((1, 2, 3, 9, 9))   # diverges inside an edge
    assert usable == 3
    _, usable = pc.match((1, 2, 5, 6, 7, 8))
    assert usable == 4
    # the split point itself holds no block, but any descendant's
    # full-prefix block covers the shorter match
    node, usable = pc.match((1, 2))
    assert usable == 2 and node.block is not None
    assert node.length >= 2
    node, usable = pc.match((9, 9))
    assert node is None and usable == 0


def test_match_prefers_covering_block_and_falls_back_to_ancestor():
    pc = PrefixCache(1 << 30)
    pc.insert((7, 8), blk())
    pc.insert((7, 8, 9, 10), blk())
    node, usable = pc.match((7, 8, 9, 10, 11))
    assert usable == 4
    # drop the deep block: the (7,8) ancestor still serves 2 positions
    pc._drop(node)
    node, usable = pc.match((7, 8, 9, 10, 11))
    assert usable == 2 and node.length == 2


def test_eviction_is_lru_under_byte_budget():
    from kubeflow_tpu.serving.prefix_cache import EVICTIONS_TOTAL

    pc = PrefixCache(2 * BLK_BYTES)
    pc.insert((1, 1, 1), blk())
    pc.insert((2, 2, 2), blk())
    assert pc.bytes == 2 * BLK_BYTES
    pc.match((1, 1, 1))                      # (1,1,1) is now most recent
    ev0 = EVICTIONS_TOTAL.get()
    pc.insert((3, 3, 3), blk())              # evicts LRU (2,2,2)
    assert pc.bytes == 2 * BLK_BYTES
    assert EVICTIONS_TOTAL.get() == ev0 + 1
    assert pc.match((2, 2, 2)) == (None, 0)
    _, usable = pc.match((1, 1, 1))
    assert usable == 3
    _, usable = pc.match((3, 3, 3))
    assert usable == 3


def test_pinned_block_survives_eviction_until_released():
    """The ISSUE invariant: eviction must never free a block an in-flight
    admission holds."""
    pc = PrefixCache(BLK_BYTES)              # budget: exactly one block
    pc.insert((1, 1, 1), blk())
    node, usable = pc.match((1, 1, 1), pin=True)
    assert usable == 3 and node.refs == 1
    # over-budget insert cannot evict the pinned node (nor itself)
    pc.insert((2, 2, 2), blk())
    assert node.block is not None
    assert pc.bytes == 2 * BLK_BYTES         # temporarily over budget
    pc.release(node)
    assert node.refs == 0
    pc.insert((3, 3, 3), blk())              # now LRU sweeps back to budget
    assert pc.bytes <= BLK_BYTES
    assert pc.match((1, 1, 1)) == (None, 0)


def test_block_larger_than_budget_not_stored():
    pc = PrefixCache(BLK_BYTES)
    assert not pc.insert((1, 2, 3), blk(snap=64))
    assert pc.bytes == 0


def test_duplicate_insert_keeps_one_block():
    pc = PrefixCache(1 << 30)
    pc.insert((4, 5, 6), blk())
    pc.insert((4, 5, 6), blk())
    assert pc.bytes == BLK_BYTES
    assert pc.stats()["blocks"] == 1


# -- engine warm path: token identity ------------------------------------------
SYS = [5, 8, 13, 21, 3, 9, 2, 17, 11, 4, 6, 12]


@pytest.fixture(scope="module")
def cold():
    from kubeflow_tpu.serving.predictor import GenerativePredictor

    p = GenerativePredictor("llama", size="tiny", max_batch=2, max_seq=64)
    yield p
    p.engine.shutdown()


@pytest.fixture(scope="module")
def warm():
    from kubeflow_tpu.serving.predictor import GenerativePredictor

    p = GenerativePredictor("llama", size="tiny", max_batch=2, max_seq=64,
                            prefix_cache_mb=8)
    assert p.engine.prefix_cache is not None
    yield p
    p.engine.shutdown()


def test_warm_greedy_identical_to_cold(cold, warm):
    a, b = SYS + [7, 1], SYS + [19, 6, 2]
    ca = cold.generate([a], max_new_tokens=10)["ids"][0]
    cb = cold.generate([b], max_new_tokens=10)["ids"][0]
    wa = warm.generate([a], max_new_tokens=10)["ids"][0]   # miss, populates
    wb = warm.generate([b], max_new_tokens=10)["ids"][0]   # partial hit
    wa2 = warm.generate([a], max_new_tokens=10)["ids"][0]  # full-prefix hit
    assert wa == ca
    assert wb == cb
    assert wa2 == ca


def test_warm_sampled_identical_to_cold(cold, warm):
    prompt = SYS + [30, 31]
    kw = dict(max_new_tokens=12, temperature=1.3, seed=5, top_k=4,
              top_p=0.9)
    want = cold.engine.submit(prompt, **kw).result(60)
    warm.engine.submit(prompt, max_new_tokens=4).result(60)  # prime cache
    got = warm.engine.submit(prompt, **kw).result(60)        # full hit
    assert got == want


def test_ragged_cobatched_hits_identical_to_solo(cold, warm):
    """Two prefix-sharing requests decoding TOGETHER on the warm engine
    must still emit exactly their solo cold-path streams."""
    import time

    a, b = SYS + [40, 41, 42], SYS + [50]
    solo = [cold.generate([p], max_new_tokens=8)["ids"][0] for p in (a, b)]
    warm.generate([SYS + [60]], max_new_tokens=2)            # prime prefix
    ra = warm.engine.submit(a, max_new_tokens=8)
    time.sleep(0.02)
    rb = warm.engine.submit(b, max_new_tokens=8)
    assert [ra.result(60), rb.result(60)] == solo


def test_full_prefix_hit_is_one_prefill_dispatch(warm):
    from kubeflow_tpu.serving.engine import (
        PREFILL_DISPATCHES,
        PREFILL_TOKENS,
        PREFIX_HITS,
    )

    prompt = SYS + [33, 34, 35]
    warm.generate([prompt], max_new_tokens=2)                # populate
    d0, t0, h0 = (PREFILL_DISPATCHES.get(), PREFILL_TOKENS.get(),
                  PREFIX_HITS.get())
    warm.generate([prompt], max_new_tokens=2)                # full hit
    assert PREFILL_DISPATCHES.get() - d0 == 1
    assert PREFIX_HITS.get() - h0 == 1
    # only the 1-token suffix ran through prefill compute
    assert PREFILL_TOKENS.get() - t0 == 1


def test_chunked_prefill_identical_to_single_dispatch(cold):
    """Long cold prompts prefill in chunks (admission no longer blocks
    decode for the whole prompt) — and chunking must not change a single
    token."""
    from kubeflow_tpu.serving.engine import ContinuousBatcher

    eng = ContinuousBatcher(cold.module, cold.params, cold.cfg,
                            max_batch=2, max_seq=64, prefill_chunk=16)
    try:
        long_prompt = list(range(1, 41))
        want = cold.generate([long_prompt], max_new_tokens=8)["ids"][0]
        assert eng.generate_sync([long_prompt], max_new_tokens=8)[0] == want
        # seeded sampling too
        kw = dict(max_new_tokens=6, temperature=0.9, seed=3)
        assert (eng.submit(long_prompt, **kw).result(60)
                == cold.engine.submit(long_prompt, **kw).result(60))
    finally:
        eng.shutdown()


def test_warm_chunked_suffix_identical(cold):
    """Prefix hit + a long suffix that itself prefills in chunks."""
    from kubeflow_tpu.serving.engine import ContinuousBatcher

    eng = ContinuousBatcher(cold.module, cold.params, cold.cfg,
                            max_batch=2, max_seq=64, prefill_chunk=16,
                            prefix_cache_bytes=8 << 20)
    try:
        shared = list(range(3, 15))                       # 12 tokens
        long_a = shared + list(range(20, 45))             # 37 tokens
        want = cold.generate([long_a], max_new_tokens=6)["ids"][0]
        eng.generate_sync([shared + [99]], max_new_tokens=2)  # cache prefix
        assert eng.generate_sync([long_a], max_new_tokens=6)[0] == want
    finally:
        eng.shutdown()


def test_pin_balance_zero_after_cancel_storm_and_shutdown():
    """ISSUE 6 satellite: every match(pin=True) must be released on EVERY
    exit path — completed, cancelled mid-decode, cancelled mid-prefill,
    queued-but-never-admitted at shutdown.  A leaked pin makes its block
    unevictable forever, so the invariant is pins == 0 whenever the
    engine is idle or shut down."""
    from kubeflow_tpu.serving.engine import ContinuousBatcher
    from kubeflow_tpu.serving.predictor import GenerativePredictor

    p = GenerativePredictor("llama", size="tiny", max_batch=2, max_seq=128,
                            prefix_cache_mb=8)
    eng = p.engine
    pc = eng.prefix_cache
    prompt = SYS + [41, 42]
    eng.submit(prompt, max_new_tokens=2).result(120)      # populate tree
    assert pc.stats()["pinned"] == 0

    # a storm of prefix-hitting requests, every one abandoned mid-flight
    reqs = [eng.submit(prompt + [50 + i], max_new_tokens=100, eos_id=0)
            for i in range(6)]
    for r in reqs:
        r.cancel()
    for r in reqs:
        assert r._done.wait(60)
    assert eng.drained(timeout=30)
    assert pc.stats()["pinned"] == 0

    # queued-but-never-admitted + mid-prefill requests at shutdown()
    eng.chaos_stall(0.5)
    held = [eng.submit(prompt + [70 + i], max_new_tokens=100, eos_id=0)
            for i in range(5)]
    eng.shutdown()
    for r in held:
        assert r._done.wait(60)
    assert pc.stats()["pinned"] == 0

    # restart() reopens with the same balanced cache
    eng.restart()
    out = eng.submit(prompt, max_new_tokens=2).result(120)
    assert out[:len(prompt)] == prompt
    assert pc.stats()["pinned"] == 0
    eng.shutdown()

    # chunked-prefill cancel: the bail-out between extend chunks must
    # release the pin it holds across dispatches
    eng2 = ContinuousBatcher(p.module, p.params, p.cfg, max_batch=1,
                             max_seq=128, prefill_chunk=16,
                             prefix_cache_bytes=8 << 20)
    try:
        shared = list(range(3, 19))                       # 16 tokens
        eng2.generate_sync([shared + [99]], max_new_tokens=2)
        long_req = eng2.submit(shared + list(range(30, 70)),
                               max_new_tokens=4)
        long_req.cancel()                # may land mid-chunked-prefill
        assert long_req._done.wait(60)
        assert eng2.drained(timeout=30)
        assert eng2.prefix_cache.stats()["pinned"] == 0
    finally:
        eng2.shutdown()


def test_prefix_metrics_exported(warm):
    from kubeflow_tpu.utils.metrics import REGISTRY

    warm.generate([SYS + [70]], max_new_tokens=2)
    text = REGISTRY.expose()
    for series in ("serving_prefix_cache_hits_total",
                   "serving_prefix_cache_misses_total",
                   "serving_prefix_cache_evictions_total",
                   "serving_prefix_cache_bytes",
                   "serving_prefill_dispatches_total"):
        assert series in text, series
    stats = warm.engine.stats()
    assert stats["prefix_cache"]["bytes"] > 0


# -- InferenceService plumb-through --------------------------------------------
def test_annotation_flows_to_predictor_args():
    from kubeflow_tpu.api import inferenceservice as api

    isvc = api.new("chat", "serving", prefix_cache_mb=64)
    assert api.prefix_cache_mb(isvc) == 64.0
    api.validate(isvc)

    from kubeflow_tpu.controllers.inferenceservice import (
        InferenceServiceController,
    )
    from kubeflow_tpu.core import APIServer

    server = APIServer()
    server.create(isvc)
    isvc = server.get(api.KIND, "chat", "serving")   # stored copy (uid)
    InferenceServiceController(server)._ensure_deployment(isvc)
    cmd = server.get("Deployment", "chat", "serving")[
        "spec"]["template"]["spec"]["containers"][0]["command"]
    assert "--prefix-cache-mb" in cmd
    assert cmd[cmd.index("--prefix-cache-mb") + 1] == "64.0"


def test_annotation_validation_rejects_garbage():
    from kubeflow_tpu.api import inferenceservice as api

    isvc = api.new("chat", "serving")
    isvc["metadata"]["annotations"] = {
        api.PREFIX_CACHE_ANNOTATION: "lots"}
    with pytest.raises(ValueError, match="number"):
        api.validate(isvc)
    isvc["metadata"]["annotations"] = {
        api.PREFIX_CACHE_ANNOTATION: "-4"}
    with pytest.raises(ValueError, match=">= 0"):
        api.validate(isvc)
    for bad in ("inf", "nan"):   # inf CrashLoops the predictor at start,
        isvc["metadata"]["annotations"] = {  # nan silently disables
            api.PREFIX_CACHE_ANNOTATION: bad}
        with pytest.raises(ValueError, match="finite"):
            api.validate(isvc)
