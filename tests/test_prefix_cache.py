"""Prefix-aware KV cache reuse (ISSUE 3, repaged in ISSUE 11): radix-tree
longest-prefix match over shared refcounted KV PAGES, LRU eviction under a
page budget with refcounted in-flight holds, and the engine's warm
admission path — whose outputs must be TOKEN-IDENTICAL to the cold path
for the same (prompt, seed, sampling params)."""

import pytest

from kubeflow_tpu.serving.page_pool import PagePool
from kubeflow_tpu.serving.prefix_cache import PrefixCache

PS = 2  # tokens per page in the unit tests


def make(max_pages: int = 1 << 20, pool_pages: int = 4096):
    pool = PagePool(pool_pages, PS, page_nbytes=64)
    return pool, PrefixCache(pool, max_pages)


def pages(pool: PagePool, tokens) -> list[int]:
    """Allocate pages covering ``tokens`` the way an admission commit
    does; the cache takes its own references at insert, so the caller's
    are dropped (pages live exactly as long as the tree wants them)."""
    n = -(-len(tokens) // PS)
    ids = pool.alloc(n)
    assert ids is not None
    return ids


def insert(pool: PrefixCache, pc, tokens) -> list[int]:
    ids = pages(pool, tokens)
    assert pc.insert(tokens, ids)
    pool.decref(ids)
    return ids


# -- radix tree unit tests -----------------------------------------------------
def test_longest_prefix_match_with_edge_splits():
    pool, pc = make()
    insert(pool, pc, (1, 2, 3, 4))
    insert(pool, pc, (1, 2, 5, 6))          # splits the (1,2,3,4) edge

    node, usable = pc.match((1, 2, 3, 4))
    assert usable == 4 and node.pages is not None
    _, usable = pc.match((1, 2, 3, 9, 9))   # diverges inside an edge
    assert usable == 3
    _, usable = pc.match((1, 2, 5, 6, 7, 8))
    assert usable == 4
    # the split point itself holds no pages, but any descendant's
    # full-prefix pages cover the shorter match
    node, usable = pc.match((1, 2))
    assert usable == 2 and node.pages is not None
    assert node.length >= 2
    node, usable = pc.match((9, 9))
    assert node is None and usable == 0


def test_match_prefers_covering_node_and_falls_back_to_ancestor():
    pool, pc = make()
    insert(pool, pc, (7, 8))
    insert(pool, pc, (7, 8, 9, 10))
    node, usable = pc.match((7, 8, 9, 10, 11))
    assert usable == 4
    # drop the deep node: the (7,8) ancestor still serves 2 positions
    pc._drop(node)
    node, usable = pc.match((7, 8, 9, 10, 11))
    assert usable == 2 and node.length == 2


def test_longer_prefix_shares_pages_by_reference():
    """The repaged tentpole invariant: a longer cached prefix holds the
    SAME page ids as the shorter one it extends (incref, no copy), and a
    shared page survives until its LAST holder is evicted."""
    pool, pc = make()
    a_ids = insert(pool, pc, (1, 2, 3, 4))
    # the longer prompt reuses A's two pages and commits one new one
    new = pool.alloc(1)
    assert pc.insert((1, 2, 3, 4, 5, 6), list(a_ids) + new)
    pool.decref(new)
    assert pc.stats()["pages"] == 3          # 3 DISTINCT pages, not 5
    for p in a_ids:
        assert pool.refcount(p) == 2         # held by both nodes
    node_a, _ = pc.match((1, 2, 3, 4))
    pc._drop(node_a)                         # evict the short prefix
    for p in a_ids:
        assert pool.refcount(p) == 1         # still alive via the long one
    node_ab, usable = pc.match((1, 2, 3, 4, 5, 6))
    assert usable == 6
    pc._drop(node_ab)
    for p in a_ids + new:
        assert pool.refcount(p) == 0         # last holder gone -> freed
    assert pool.free_count == pool.num_pages - 1


def test_eviction_is_lru_under_page_budget():
    from kubeflow_tpu.serving.prefix_cache import EVICTIONS_TOTAL

    pool, pc = make(max_pages=4)             # room for two 2-page prefixes
    insert(pool, pc, (1, 1, 1))
    insert(pool, pc, (2, 2, 2))
    assert pc.stats()["pages"] == 4
    pc.match((1, 1, 1))                      # (1,1,1) is now most recent
    ev0 = EVICTIONS_TOTAL.get()
    insert(pool, pc, (3, 3, 3))              # evicts LRU (2,2,2)
    assert pc.stats()["pages"] == 4
    assert EVICTIONS_TOTAL.get() == ev0 + 1
    assert pc.match((2, 2, 2)) == (None, 0)
    _, usable = pc.match((1, 1, 1))
    assert usable == 3
    _, usable = pc.match((3, 3, 3))
    assert usable == 3
    # evicted pages went back to the pool, not just out of the tree
    assert pool.free_count == pool.num_pages - 1 - 4


def test_pinned_node_survives_eviction_until_released():
    """The ISSUE invariant: eviction must never free pages an in-flight
    admission holds."""
    pool, pc = make(max_pages=2)             # budget: exactly one prefix
    insert(pool, pc, (1, 1, 1))
    node, usable = pc.match((1, 1, 1), pin=True)
    assert usable == 3 and node.refs == 1
    # over-budget insert cannot evict the pinned node (nor itself)
    insert(pool, pc, (2, 2, 2))
    assert node.pages is not None
    assert pc.stats()["pages"] == 4          # temporarily over budget
    assert pc.stats()["pinned"] == 1
    assert not pc.evict_lru() or node.pages is not None
    pc.release(node)
    assert node.refs == 0
    insert(pool, pc, (3, 3, 3))              # now LRU sweeps back to budget
    assert pc.stats()["pages"] <= 2
    assert pc.match((1, 1, 1)) == (None, 0)


def test_prefix_larger_than_budget_not_stored():
    pool, pc = make(max_pages=1)
    ids = pages(pool, (1, 2, 3))             # needs 2 pages > budget 1
    assert not pc.insert((1, 2, 3), ids)
    pool.decref(ids)
    assert pc.stats()["pages"] == 0
    assert pool.free_count == pool.num_pages - 1


def test_duplicate_insert_keeps_one_node():
    pool, pc = make()
    insert(pool, pc, (4, 5, 6))
    ids2 = pages(pool, (4, 5, 6))
    assert pc.insert((4, 5, 6), ids2)        # refresh, not re-store
    pool.decref(ids2)
    assert pc.stats()["pages"] == 2
    assert pc.stats()["nodes"] == 1
    assert pool.free_count == pool.num_pages - 1 - 2


def test_pool_refcount_guards():
    pool = PagePool(8, PS)
    ids = pool.alloc(2)
    with pytest.raises(ValueError):
        pool.decref([99] if 99 < pool.num_pages else [7])
    pool.decref(ids)
    with pytest.raises(ValueError):
        pool.decref(ids)                     # double free
    with pytest.raises(ValueError):
        pool.incref(ids)                     # incref of free page
    assert pool.alloc(99) is None            # over-ask fails whole


# -- engine warm path: token identity ------------------------------------------
SYS = [5, 8, 13, 21, 3, 9, 2, 17, 11, 4, 6, 12]


@pytest.fixture(scope="module")
def cold():
    from kubeflow_tpu.serving.predictor import GenerativePredictor

    p = GenerativePredictor("llama", size="tiny", max_batch=2, max_seq=64)
    yield p
    p.engine.shutdown()


@pytest.fixture(scope="module")
def warm():
    from kubeflow_tpu.serving.predictor import GenerativePredictor

    p = GenerativePredictor("llama", size="tiny", max_batch=2, max_seq=64,
                            prefix_cache_mb=8)
    assert p.engine.prefix_cache is not None
    yield p
    p.engine.shutdown()


def test_warm_greedy_identical_to_cold(cold, warm):
    a, b = SYS + [7, 1], SYS + [19, 6, 2]
    ca = cold.generate([a], max_new_tokens=10)["ids"][0]
    cb = cold.generate([b], max_new_tokens=10)["ids"][0]
    wa = warm.generate([a], max_new_tokens=10)["ids"][0]   # miss, populates
    wb = warm.generate([b], max_new_tokens=10)["ids"][0]   # partial hit
    wa2 = warm.generate([a], max_new_tokens=10)["ids"][0]  # full-prefix hit
    assert wa == ca
    assert wb == cb
    assert wa2 == ca


def test_warm_sampled_identical_to_cold(cold, warm):
    prompt = SYS + [30, 31]
    kw = dict(max_new_tokens=12, temperature=1.3, seed=5, top_k=4,
              top_p=0.9)
    want = cold.engine.submit(prompt, **kw).result(60)
    warm.engine.submit(prompt, max_new_tokens=4).result(60)  # prime cache
    got = warm.engine.submit(prompt, **kw).result(60)        # full hit
    assert got == want


def test_ragged_cobatched_hits_identical_to_solo(cold, warm):
    """Two prefix-sharing requests decoding TOGETHER on the warm engine
    must still emit exactly their solo cold-path streams."""
    import time

    a, b = SYS + [40, 41, 42], SYS + [50]
    solo = [cold.generate([p], max_new_tokens=8)["ids"][0] for p in (a, b)]
    warm.generate([SYS + [60]], max_new_tokens=2)            # prime prefix
    ra = warm.engine.submit(a, max_new_tokens=8)
    time.sleep(0.02)
    rb = warm.engine.submit(b, max_new_tokens=8)
    assert [ra.result(60), rb.result(60)] == solo


def test_full_prefix_hit_is_one_prefill_dispatch(warm):
    from kubeflow_tpu.serving.engine import (
        PREFILL_DISPATCHES,
        PREFILL_TOKENS,
        PREFIX_HITS,
    )

    prompt = SYS + [33, 34, 35]
    warm.generate([prompt], max_new_tokens=2)                # populate
    d0, t0, h0 = (PREFILL_DISPATCHES.get(), PREFILL_TOKENS.get(),
                  PREFIX_HITS.get())
    warm.generate([prompt], max_new_tokens=2)                # full hit
    assert PREFILL_DISPATCHES.get() - d0 == 1
    assert PREFIX_HITS.get() - h0 == 1
    # only the 1-token suffix ran through prefill compute
    assert PREFILL_TOKENS.get() - t0 == 1


def test_chunked_prefill_identical_to_single_dispatch(cold):
    """Long cold prompts prefill in chunks (admission no longer blocks
    decode for the whole prompt) — and chunking must not change a single
    token."""
    from kubeflow_tpu.serving.engine import ContinuousBatcher

    eng = ContinuousBatcher(cold.module, cold.params, cold.cfg,
                            max_batch=2, max_seq=64, prefill_chunk=16)
    try:
        long_prompt = list(range(1, 41))
        want = cold.generate([long_prompt], max_new_tokens=8)["ids"][0]
        assert eng.generate_sync([long_prompt], max_new_tokens=8)[0] == want
        # seeded sampling too
        kw = dict(max_new_tokens=6, temperature=0.9, seed=3)
        assert (eng.submit(long_prompt, **kw).result(60)
                == cold.engine.submit(long_prompt, **kw).result(60))
    finally:
        eng.shutdown()


def test_warm_chunked_suffix_identical(cold):
    """Prefix hit + a long suffix that itself prefills in chunks."""
    from kubeflow_tpu.serving.engine import ContinuousBatcher

    eng = ContinuousBatcher(cold.module, cold.params, cold.cfg,
                            max_batch=2, max_seq=64, prefill_chunk=16,
                            prefix_cache_bytes=8 << 20)
    try:
        shared = list(range(3, 15))                       # 12 tokens
        long_a = shared + list(range(20, 45))             # 37 tokens
        want = cold.generate([long_a], max_new_tokens=6)["ids"][0]
        eng.generate_sync([shared + [99]], max_new_tokens=2)  # cache prefix
        assert eng.generate_sync([long_a], max_new_tokens=6)[0] == want
    finally:
        eng.shutdown()


def test_pin_balance_zero_after_cancel_storm_and_shutdown():
    """ISSUE 6 satellite: every match(pin=True) must be released on EVERY
    exit path — completed, cancelled mid-decode, cancelled mid-prefill,
    queued-but-never-admitted at shutdown.  A leaked pin makes its block
    unevictable forever, so the invariant is pins == 0 whenever the
    engine is idle or shut down."""
    from kubeflow_tpu.serving.engine import ContinuousBatcher
    from kubeflow_tpu.serving.predictor import GenerativePredictor

    p = GenerativePredictor("llama", size="tiny", max_batch=2, max_seq=128,
                            prefix_cache_mb=8)
    eng = p.engine
    pc = eng.prefix_cache
    prompt = SYS + [41, 42]
    eng.submit(prompt, max_new_tokens=2).result(120)      # populate tree
    assert pc.stats()["pinned"] == 0

    # a storm of prefix-hitting requests, every one abandoned mid-flight
    reqs = [eng.submit(prompt + [50 + i], max_new_tokens=100, eos_id=0)
            for i in range(6)]
    for r in reqs:
        r.cancel()
    for r in reqs:
        assert r._done.wait(60)
    assert eng.drained(timeout=30)
    assert pc.stats()["pinned"] == 0

    # queued-but-never-admitted + mid-prefill requests at shutdown()
    eng.chaos_stall(0.5)
    held = [eng.submit(prompt + [70 + i], max_new_tokens=100, eos_id=0)
            for i in range(5)]
    eng.shutdown()
    for r in held:
        assert r._done.wait(60)
    assert pc.stats()["pinned"] == 0

    # restart() reopens with the same balanced cache
    eng.restart()
    out = eng.submit(prompt, max_new_tokens=2).result(120)
    assert out[:len(prompt)] == prompt
    assert pc.stats()["pinned"] == 0
    eng.shutdown()

    # chunked-prefill cancel: the bail-out between extend chunks must
    # release the pin it holds across dispatches
    eng2 = ContinuousBatcher(p.module, p.params, p.cfg, max_batch=1,
                             max_seq=128, prefill_chunk=16,
                             prefix_cache_bytes=8 << 20)
    try:
        shared = list(range(3, 19))                       # 16 tokens
        eng2.generate_sync([shared + [99]], max_new_tokens=2)
        long_req = eng2.submit(shared + list(range(30, 70)),
                               max_new_tokens=4)
        long_req.cancel()                # may land mid-chunked-prefill
        assert long_req._done.wait(60)
        assert eng2.drained(timeout=30)
        assert eng2.prefix_cache.stats()["pinned"] == 0
    finally:
        eng2.shutdown()


def test_prefix_metrics_exported(warm):
    from kubeflow_tpu.utils.metrics import REGISTRY

    warm.generate([SYS + [70]], max_new_tokens=2)
    text = REGISTRY.expose()
    for series in ("serving_prefix_cache_hits_total",
                   "serving_prefix_cache_misses_total",
                   "serving_prefix_cache_evictions_total",
                   "serving_prefix_cache_bytes",
                   "serving_prefill_dispatches_total"):
        assert series in text, series
    stats = warm.engine.stats()
    assert stats["prefix_cache"]["bytes"] > 0


# -- InferenceService plumb-through --------------------------------------------
def test_annotation_flows_to_predictor_args():
    from kubeflow_tpu.api import inferenceservice as api

    isvc = api.new("chat", "serving", prefix_cache_mb=64)
    assert api.prefix_cache_mb(isvc) == 64.0
    api.validate(isvc)

    from kubeflow_tpu.controllers.inferenceservice import (
        InferenceServiceController,
    )
    from kubeflow_tpu.core import APIServer

    server = APIServer()
    server.create(isvc)
    isvc = server.get(api.KIND, "chat", "serving")   # stored copy (uid)
    InferenceServiceController(server)._ensure_deployment(isvc)
    cmd = server.get("Deployment", "chat", "serving")[
        "spec"]["template"]["spec"]["containers"][0]["command"]
    assert "--prefix-cache-mb" in cmd
    assert cmd[cmd.index("--prefix-cache-mb") + 1] == "64.0"


def test_annotation_validation_rejects_garbage():
    from kubeflow_tpu.api import inferenceservice as api

    isvc = api.new("chat", "serving")
    isvc["metadata"]["annotations"] = {
        api.PREFIX_CACHE_ANNOTATION: "lots"}
    with pytest.raises(ValueError, match="number"):
        api.validate(isvc)
    isvc["metadata"]["annotations"] = {
        api.PREFIX_CACHE_ANNOTATION: "-4"}
    with pytest.raises(ValueError, match=">= 0"):
        api.validate(isvc)
    for bad in ("inf", "nan"):   # inf CrashLoops the predictor at start,
        isvc["metadata"]["annotations"] = {  # nan silently disables
            api.PREFIX_CACHE_ANNOTATION: bad}
        with pytest.raises(ValueError, match="finite"):
            api.validate(isvc)


def test_kv_page_and_speculative_annotations_flow_to_args():
    """ISSUE 11: serving.kubeflow.org/kv-page-size and
    /speculative-tokens follow the prefix-cache-mb pattern end to end:
    api constructor -> annotation -> controller -> predictor args."""
    from kubeflow_tpu.api import inferenceservice as api

    isvc = api.new("chat", "serving", prefix_cache_mb=64,
                   kv_page_size=32, speculative_tokens=8)
    assert api.kv_page_size(isvc) == 32
    assert api.speculative_tokens(isvc) == 8
    api.validate(isvc)

    from kubeflow_tpu.controllers.inferenceservice import (
        InferenceServiceController,
    )
    from kubeflow_tpu.core import APIServer

    server = APIServer()
    server.create(isvc)
    isvc = server.get(api.KIND, "chat", "serving")
    InferenceServiceController(server)._ensure_deployment(isvc)
    cmd = server.get("Deployment", "chat", "serving")[
        "spec"]["template"]["spec"]["containers"][0]["command"]
    assert cmd[cmd.index("--kv-page-size") + 1] == "32"
    assert cmd[cmd.index("--speculative-tokens") + 1] == "8"
    # absent annotations add no flags (engine defaults rule)
    plain = api.new("plain", "serving")
    server.create(plain)
    plain = server.get(api.KIND, "plain", "serving")
    InferenceServiceController(server)._ensure_deployment(plain)
    cmd2 = server.get("Deployment", "plain", "serving")[
        "spec"]["template"]["spec"]["containers"][0]["command"]
    assert "--kv-page-size" not in cmd2
    assert "--speculative-tokens" not in cmd2


def test_kv_page_and_speculative_annotation_validation():
    from kubeflow_tpu.api import inferenceservice as api

    isvc = api.new("chat", "serving")
    for ann in (api.KV_PAGE_SIZE_ANNOTATION,
                api.SPECULATIVE_TOKENS_ANNOTATION):
        isvc["metadata"]["annotations"] = {ann: "many"}
        with pytest.raises(ValueError, match="integer"):
            api.validate(isvc)
        isvc["metadata"]["annotations"] = {ann: "-4"}
        with pytest.raises(ValueError, match=">= 0"):
            api.validate(isvc)
    isvc["metadata"]["annotations"] = {
        api.KV_PAGE_SIZE_ANNOTATION: "16",
        api.SPECULATIVE_TOKENS_ANNOTATION: "0"}
    api.validate(isvc)


def test_predictor_plumbs_page_and_spec_args():
    from kubeflow_tpu.serving.predictor import GenerativePredictor

    p = GenerativePredictor("llama", size="tiny", max_batch=1, max_seq=64,
                            kv_page_size=8, speculative_tokens=4)
    try:
        assert p.engine.page_size == 8
        assert p.engine.spec_max == 4
    finally:
        p.engine.shutdown()
