"""Controllers over the wire: the KubeStore k8s-REST adapter (VERDICT r2 #4).

The reference's controllers speak REST to a kube-apiserver
(notebook_controller.go:119-198, tested against envtest's real apiserver).
Here the SAME controller classes that normally hold an in-process store run
against ``KubeStore`` — HTTP to a remote API server served by our own
``core.httpapi`` facade (the envtest move: real API semantics, no cluster).
This is the bridge that lets ``manifests/`` deploy a control plane whose
executors live on other machines (TPU-VM node agents).
"""

import pytest
from conftest import poll_until as wait

from kubeflow_tpu.api import jaxjob as jaxjob_api
from kubeflow_tpu.controllers.executor import FakeExecutor, LocalExecutor
from kubeflow_tpu.controllers.jaxjob import JAXJobController
from kubeflow_tpu.controllers.notebook import NotebookController
from kubeflow_tpu.controllers import workloads
from kubeflow_tpu.core import APIServer, Manager, quota
from kubeflow_tpu.core.httpapi import RestAPI, serve
from kubeflow_tpu.core.kubeclient import KubeStore
from kubeflow_tpu.core.store import Conflict, Invalid, NotFound


@pytest.fixture()
def make_remote():
    """The 'real cluster': APIServer + admission, served over HTTP, with a
    FakeExecutor manager as its kubelet."""
    cleanup = []

    def build(**executor_kw):
        server = APIServer()
        quota.register(server)
        mgr = Manager(server)
        mgr.add(FakeExecutor(server, **executor_kw))
        mgr.start()
        httpd, _ = serve(RestAPI(server), 0)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        cleanup.append((httpd, mgr))
        return server, base

    yield build
    for httpd, mgr in cleanup:
        httpd.shutdown()
        mgr.stop()


def test_store_surface_over_http(make_remote):
    server, base = make_remote()
    store = KubeStore(base)
    created = store.create({"kind": "ConfigMap", "apiVersion": "v1",
                            "metadata": {"name": "c", "namespace": "d"},
                            "spec": {"x": 1}})
    assert created["metadata"]["resourceVersion"]
    got = store.get("ConfigMap", "c", "d")
    assert got["spec"] == {"x": 1}

    # optimistic concurrency crosses the wire: stale rv -> Conflict
    stale = dict(got)
    store.update(got)  # no-op ok
    got2 = store.get("ConfigMap", "c", "d")
    got2["spec"] = {"x": 2}
    store.update(got2)
    stale["spec"] = {"x": 3}
    with pytest.raises(Conflict):
        store.update(stale)

    # label-selector list
    store.create({"kind": "ConfigMap", "apiVersion": "v1",
                  "metadata": {"name": "l", "namespace": "d",
                               "labels": {"app": "a"}}, "spec": {}})
    items = store.list("ConfigMap", namespace="d",
                       label_selector={"matchLabels": {"app": "a"}})
    assert [o["metadata"]["name"] for o in items] == ["l"]

    store.delete("ConfigMap", "c", "d")
    with pytest.raises(NotFound):
        store.get("ConfigMap", "c", "d")

    # server-side admission still guards the wire path
    server.register_validating_hook(
        lambda o: (_ for _ in ()).throw(Invalid("nope"))
        if o.get("kind") == "Forbidden" else None)
    with pytest.raises(Invalid):
        store.create({"kind": "Forbidden", "apiVersion": "v1",
                      "metadata": {"name": "f", "namespace": "d"},
                      "spec": {}})


def test_watch_streams_over_http(make_remote):
    server, base = make_remote()
    store = KubeStore(base)
    w = store.watch(kinds=["ConfigMap"])
    try:
        store.create({"kind": "ConfigMap", "apiVersion": "v1",
                      "metadata": {"name": "w", "namespace": "d"},
                      "spec": {}})
        ev = w.next(timeout=5)
        assert ev is not None and ev.type == "ADDED"
        assert ev.object["metadata"]["name"] == "w"
        store.delete("ConfigMap", "w", "d")
        ev = w.next(timeout=5)
        assert ev is not None and ev.type == "DELETED"
    finally:
        w.stop()


def test_notebook_controller_against_http_facade(make_remote):
    """The notebook controller subset (VERDICT #4 'Done' criterion): CR ->
    StatefulSet -> pod -> status mirror -> stop annotation, all over HTTP."""
    server, base = make_remote(complete=False)  # notebooks run forever
    store = KubeStore(base)
    mgr = Manager(store)
    mgr.add(NotebookController(store))
    workloads.register(store, mgr)
    mgr.start()
    try:
        store.create({"kind": "Notebook", "apiVersion": "kubeflow.org/v1",
                      "metadata": {"name": "nb", "namespace": "team"},
                      "spec": {"template": {"spec": {"containers": [
                          {"name": "nb", "image": "jax-nb:v1"}]}}}})
        nb = wait(lambda: (lambda o: o if o.get("status", {})
                           .get("readyReplicas") else None)(
            store.get("Notebook", "nb", "team")), timeout=20)
        assert nb["status"]["containerState"] == {"running": {}}
        # children materialized in the REMOTE store
        server.get("StatefulSet", "nb", "team")
        server.get("Service", "nb", "team")
        server.get("VirtualService", "notebook-nb", "team")

        # stop annotation -> replicas 0 across the wire
        fresh = store.get("Notebook", "nb", "team")
        fresh["metadata"]["annotations"][
            "kubeflow-resource-stopped"] = "2026-07-29T00:00:00Z"
        store.update(fresh)
        wait(lambda: (server.get("StatefulSet", "nb", "team")["spec"]
                      ["replicas"] == 0) or None, timeout=20)
    finally:
        mgr.stop()
        store.close()


def test_jaxjob_gang_against_http_facade(make_remote):
    server, base = make_remote()
    store = KubeStore(base)
    mgr = Manager(store)
    mgr.add(JAXJobController(store))
    mgr.start()
    try:
        store.create(jaxjob_api.new("train", "team", topology="v5e-8"))
        job = wait(lambda: (lambda o: o if o.get("status", {})
                            .get("phase") == "Succeeded" else None)(
            store.get("JAXJob", "train", "team")), timeout=30)
        assert job["status"]["workers"]["total"] == 2  # v5e-8 = 2 hosts
        assert job["status"]["result"]["samples_per_sec"] == 100.0
    finally:
        mgr.stop()
        store.close()


def test_split_process_kubelet():
    """LocalExecutor(KubeStore) IS the KubeExecutor: pod state lives in the
    remote apiserver, the process runs where the executor agent does — the
    TPU-VM node-agent shape."""
    server = APIServer()
    httpd, _ = serve(RestAPI(server), 0)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    store = KubeStore(base)
    mgr = Manager(store)
    mgr.add(LocalExecutor(store))
    mgr.start()
    try:
        store.create({"kind": "Pod", "apiVersion": "v1",
                      "metadata": {"name": "p", "namespace": "d"},
                      "spec": {"containers": [{
                          "name": "c", "image": "i",
                          "command": ["python", "-c",
                                      "print('{\"ok\": true}')"]}]}})
        pod = wait(lambda: (lambda o: o if o.get("status", {})
                            .get("phase") == "Succeeded" else None)(
            server.get("Pod", "p", "d")), timeout=20)
        assert pod["status"]["result"] == {"ok": True}
    finally:
        mgr.stop()
        store.close()
        httpd.shutdown()


def test_watch_survives_facade_restart():
    """VERDICT r3 #5: the pump thread must not die silently on connection
    loss.  Kill the facade mid-watch, bring it back on the same port:
    the watch reconnects, re-lists (sync MODIFIED for survivors, DELETED
    for objects that vanished during the gap), and live events flow.
    The event window is pinned to ONE entry so the gap provably expires
    (410) — this test is about the RELIST path; short gaps now resume
    and replay instead (test_watch_resume_replays_gap_without_relist)."""
    from kubeflow_tpu.core import watchcache

    server = APIServer()
    watchcache.attach(server, window=1)
    httpd, _ = serve(RestAPI(server), 0)
    port = httpd.server_address[1]
    store = KubeStore(f"http://127.0.0.1:{port}")
    w = store.watch(kinds=["ConfigMap"])
    try:
        for name in ("keep", "gone"):
            store.create({"kind": "ConfigMap", "apiVersion": "v1",
                          "metadata": {"name": name, "namespace": "d"},
                          "spec": {}})
        assert w.next(timeout=5).type == "ADDED"
        assert w.next(timeout=5).type == "ADDED"

        # facade dies: stop accepting AND sever the established stream
        # (a process restart kills its sockets; shutdown() alone leaves
        # the old connection thread serving)
        httpd.shutdown()
        httpd.server_close()
        w._resp.close()
        server.delete("ConfigMap", "gone", "d")
        # widen the gap past the 1-event window: resume must 410
        server.patch_status("ConfigMap", "keep", "d", {"n": 1})
        server.patch_status("ConfigMap", "keep", "d", {"n": 2})
        httpd, _ = serve(RestAPI(server), port)  # same port, same store

        events = {}
        deadline = 15
        import time as _t
        t0 = _t.monotonic()
        while _t.monotonic() - t0 < deadline:
            ev = w.next(timeout=1.0)
            if ev is None:
                continue
            events[(ev.type, ev.object["metadata"]["name"])] = ev
            if (("MODIFIED", "keep") in events
                    and ("DELETED", "gone") in events):
                break
        assert ("MODIFIED", "keep") in events, events  # re-list sync
        assert ("DELETED", "gone") in events, events   # gap deletion

        # live events flow again on the reconnected stream
        store.create({"kind": "ConfigMap", "apiVersion": "v1",
                      "metadata": {"name": "after", "namespace": "d"},
                      "spec": {}})
        got = wait(lambda: next(
            (e for e in iter(lambda: w.next(timeout=0.5), None)
             if e.object["metadata"]["name"] == "after"), None), timeout=10)
        assert got.type == "ADDED"
    finally:
        w.stop()
        httpd.shutdown()


def test_controller_reconverges_after_facade_restart():
    """A NotebookController on a KubeStore keeps reconciling after the
    facade bounces: a Notebook created post-restart still materializes its
    StatefulSet (the silent-deaf-watch failure mode, fixed)."""
    server = APIServer()
    quota.register(server)
    remote_mgr = Manager(server)
    remote_mgr.add(FakeExecutor(server, complete=False))
    remote_mgr.start()
    httpd, _ = serve(RestAPI(server), 0)
    port = httpd.server_address[1]
    store = KubeStore(f"http://127.0.0.1:{port}")
    mgr = Manager(store)
    mgr.add(NotebookController(store))
    workloads.register(store, mgr)
    mgr.start()
    try:
        store.create({"kind": "Notebook", "apiVersion": "kubeflow.org/v1",
                      "metadata": {"name": "nb1", "namespace": "t"},
                      "spec": {"template": {"spec": {"containers": [
                          {"name": "nb1", "image": "i"}]}}}})
        wait(lambda: _exists(store, "StatefulSet", "nb1", "t"), timeout=10)

        httpd.shutdown()
        httpd.server_close()
        for watch in list(store._watches):  # a restart severs live sockets
            watch._resp.close()
        httpd, _ = serve(RestAPI(server), port)

        # created AFTER the bounce: only a reconnected watch sees it
        store.create({"kind": "Notebook", "apiVersion": "kubeflow.org/v1",
                      "metadata": {"name": "nb2", "namespace": "t"},
                      "spec": {"template": {"spec": {"containers": [
                          {"name": "nb2", "image": "i"}]}}}})
        wait(lambda: _exists(store, "StatefulSet", "nb2", "t"), timeout=20)
    finally:
        mgr.stop()
        remote_mgr.stop()
        httpd.shutdown()
        store.close()


def _exists(store, kind, name, ns):
    try:
        store.get(kind, name, ns)
        return True
    except NotFound:
        return False


def test_kindless_watch_resyncs_after_facade_restart():
    """VERDICT r4 weak #4: a kind-filterless watch must NOT silently lose
    the gap — on reconnect it enumerates the server's kinds (GET /apis
    discovery) and re-lists everything.  And (ADVICE r4) synthesized
    DELETED events carry the last-seen labels/ownerReferences so
    owner/label watch-mappers can still derive reconcile Requests.
    Window pinned to one entry: the gap must take the 410-relist path,
    not the (newer) exact-replay resume."""
    from kubeflow_tpu.core import watchcache

    server = APIServer()
    watchcache.attach(server, window=1)
    httpd, _ = serve(RestAPI(server), 0)
    port = httpd.server_address[1]
    store = KubeStore(f"http://127.0.0.1:{port}")
    assert store.kinds() == []  # discovery endpoint exists and is empty
    w = store.watch()  # NO kind filter
    try:
        store.create({"kind": "ConfigMap", "apiVersion": "v1",
                      "metadata": {"name": "keep", "namespace": "d"},
                      "spec": {}})
        store.create({"kind": "Pod", "apiVersion": "v1",
                      "metadata": {"name": "gone", "namespace": "d",
                                   "labels": {"notebook-name": "nb9"},
                                   "ownerReferences": [
                                       {"kind": "Notebook", "name": "nb9",
                                        "uid": "u-nb9"}]},
                      "spec": {}})
        assert w.next(timeout=5).type == "ADDED"
        assert w.next(timeout=5).type == "ADDED"
        assert sorted(store.kinds()) == ["ConfigMap", "Pod"]

        httpd.shutdown()
        httpd.server_close()
        w._resp.close()
        server.delete("Pod", "gone", "d")  # the ONLY Pod vanishes
        # widen the gap past the 1-event window: resume must 410
        server.patch_status("ConfigMap", "keep", "d", {"n": 1})
        server.patch_status("ConfigMap", "keep", "d", {"n": 2})
        httpd, _ = serve(RestAPI(server), port)

        events = {}
        import time as _t
        t0 = _t.monotonic()
        while _t.monotonic() - t0 < 15:
            ev = w.next(timeout=1.0)
            if ev is None:
                continue
            events[(ev.type, ev.object["metadata"]["name"])] = ev
            if (("MODIFIED", "keep") in events
                    and ("DELETED", "gone") in events):
                break
        assert ("MODIFIED", "keep") in events, events
        deleted = events.get(("DELETED", "gone"))
        assert deleted is not None, events
        md = deleted.object["metadata"]
        # cached metadata rides the synthesized event
        assert md["labels"] == {"notebook-name": "nb9"}
        assert md["ownerReferences"][0]["uid"] == "u-nb9"
    finally:
        w.stop()
        httpd.shutdown()


# -- watch-cache resume + pagination (ISSUE 13) --------------------------------

def _stop(httpd, watch=None):
    httpd.shutdown()
    httpd.server_close()  # release the port for the bounce
    if watch is not None:
        # the established stream socket survives the listener's death;
        # sever it so the client actually experiences the outage
        watch._resp.close()


def _restart_on_port(server, port):
    """Simulate an apiserver bounce: a new listener on the same port."""
    import time as _time

    for _ in range(50):
        try:
            httpd, _ = serve(RestAPI(server), port)
            return httpd
        except OSError:
            _time.sleep(0.05)
    raise RuntimeError(f"port {port} never freed")


def test_list_auto_paginates_with_limit():
    from kubeflow_tpu.core import watchcache

    server = APIServer()
    for i in range(23):
        server.create({"kind": "CM", "apiVersion": "v1",
                       "metadata": {"name": f"c{i:02d}", "namespace": "d"},
                       "spec": {"i": i}})
    httpd, _ = serve(RestAPI(server), 0)
    try:
        store = KubeStore(f"http://127.0.0.1:{httpd.server_address[1]}")
        scanned0 = watchcache.SCANNED.get()
        items = store.list("CM", namespace="d", limit=5)
        assert [o["metadata"]["name"] for o in items] == [
            f"c{i:02d}" for i in range(23)]
        # the server walked the kind once, not once per page
        assert watchcache.SCANNED.get() - scanned0 == 23
        page, cont, rv = store.list_page("CM", namespace="d", limit=10)
        assert len(page) == 10 and cont and rv
    finally:
        httpd.shutdown()


def test_watch_resume_replays_gap_without_relist(monkeypatch):
    """A short outage with a large window: the reconnect RESUMES and the
    server replays exactly the missed events — no synthesized MODIFIED
    flood from a re-list."""
    from kubeflow_tpu.core import watchcache
    from kubeflow_tpu.core.kubeclient import WATCH_RESUMES

    server = APIServer()
    watchcache.attach(server, window=1024)
    server.create({"kind": "CM", "apiVersion": "v1",
                   "metadata": {"name": "pre", "namespace": "d"},
                   "spec": {}})
    httpd, _ = serve(RestAPI(server), 0)
    port = httpd.server_address[1]
    store = KubeStore(f"http://127.0.0.1:{port}")
    w = store.watch(kinds=["CM"])
    try:
        ev = w.next(timeout=5)
        assert ev is None or ev.type  # may or may not see 'pre'
        server.create({"kind": "CM", "apiVersion": "v1",
                       "metadata": {"name": "before", "namespace": "d"},
                       "spec": {}})
        assert wait(lambda: w.next(timeout=1))  # position advances
        resumed0 = WATCH_RESUMES.get("resumed")
        _stop(httpd, w)  # sever, with a real gap behind it
        for i in range(3):
            server.create({"kind": "CM", "apiVersion": "v1",
                           "metadata": {"name": f"gap{i}",
                                        "namespace": "d"}, "spec": {}})
        httpd = _restart_on_port(server, port)
        got = []
        deadline = 20
        while len(got) < 3:
            ev = w.next(timeout=1)
            deadline -= 1
            assert deadline > 0, f"only saw {got}"
            if ev is not None and ev.object["metadata"][
                    "name"].startswith("gap"):
                got.append((ev.type, ev.object["metadata"]["name"]))
        # the gap replayed EXACTLY: ADDED events in order, not MODIFIED
        # relist synthetics
        assert got == [("ADDED", f"gap{i}") for i in range(3)]
        assert WATCH_RESUMES.get("resumed") >= resumed0 + 1
    finally:
        w.stop()
        httpd.shutdown()


def test_watch_resume_after_window_eviction_falls_back_to_relist():
    """Regression (ISSUE 13 satellite): an outage longer than the event
    window answers 410; the client must re-list — synthesizing DELETED
    for vanished objects — instead of hanging or silently losing the
    gap."""
    from kubeflow_tpu.core import watchcache
    from kubeflow_tpu.core.kubeclient import WATCH_RESUMES

    server = APIServer()
    watchcache.attach(server, window=4)
    for i in range(3):
        server.create({"kind": "CM", "apiVersion": "v1",
                       "metadata": {"name": f"c{i}", "namespace": "d"},
                       "spec": {}})
    httpd, _ = serve(RestAPI(server), 0)
    port = httpd.server_address[1]
    store = KubeStore(f"http://127.0.0.1:{port}")
    w = store.watch(kinds=["CM"])
    try:
        # the watch must OBSERVE c1 before the gap: the re-list can only
        # synthesize DELETED for objects it knew were alive
        server.patch_status("CM", "c1", "d", {"seen": True})
        server.patch_status("CM", "c0", "d", {"seen": True})
        assert wait(lambda: w.next(timeout=1))  # position advances
        assert wait(lambda: w.next(timeout=1))
        expired0 = WATCH_RESUMES.get("expired")
        _stop(httpd, w)
        # more events than the window retains, including a delete the
        # re-list must synthesize
        server.delete("CM", "c1", "d")
        for i in range(6):
            server.patch_status("CM", "c0", "d", {"n": i})
        httpd = _restart_on_port(server, port)
        seen_delete = wait(
            lambda: next((ev for ev in iter(
                lambda: w.next(timeout=0.5), None)
                if ev.type == "DELETED"
                and ev.object["metadata"]["name"] == "c1"), None),
            timeout=20)
        assert seen_delete is not None
        assert WATCH_RESUMES.get("expired") >= expired0 + 1
    finally:
        w.stop()
        httpd.shutdown()


def test_delete_uid_precondition_over_http(make_remote):
    """The k8s DeleteOptions.Preconditions.UID shape crosses the wire:
    a uid-guarded delete kills only THAT incarnation — a same-name
    replacement answers 409 Conflict, exactly what the preemption
    controller's eviction relies on to never kill a recreated pod."""
    server, base = make_remote()
    store = KubeStore(base)
    first = store.create({"kind": "ConfigMap", "apiVersion": "v1",
                          "metadata": {"name": "c", "namespace": "d"},
                          "spec": {}})
    store.delete("ConfigMap", "c", "d")
    second = store.create({"kind": "ConfigMap", "apiVersion": "v1",
                           "metadata": {"name": "c", "namespace": "d"},
                           "spec": {}})
    assert second["metadata"]["uid"] != first["metadata"]["uid"]
    with pytest.raises(Conflict):
        store.delete("ConfigMap", "c", "d", uid=first["metadata"]["uid"])
    store.delete("ConfigMap", "c", "d", uid=second["metadata"]["uid"])
    with pytest.raises(NotFound):
        store.get("ConfigMap", "c", "d")


# -- reconnect backoff + fencing epochs (ISSUE 20) -----------------------------

class TestBackoff:
    def test_seeded_jitter_exponential_and_capped(self):
        import random

        from kubeflow_tpu.core.kubeclient import _Backoff

        a = _Backoff(rng=random.Random(7))
        b = _Backoff(rng=random.Random(7))
        seq = [a.next() for _ in range(12)]
        assert seq == [b.next() for _ in range(12)]  # same seed, same run
        # each delay jitters in [0.5, 1.0) of the exponential rung
        for i, d in enumerate(seq):
            rung = min(5.0, 0.2 * (2 ** i))
            assert rung * 0.5 <= d < rung, (i, d)
        assert max(seq) < 5.0  # capped
        assert seq[5] > seq[0] * 4  # actually grows
        a.reset()
        nxt = a.next()
        assert 0.1 <= nxt < 0.2  # reset re-arms the ladder

    def test_flapping_server_backs_off_instead_of_hot_spinning(self):
        """Regression (ISSUE 20 satellite): a leader that ACCEPTS the dial
        but drops the stream before a single byte used to reset the old
        fixed retry ladder on every successful connect — a hot-spinning
        dial loop against a flapping leader.  The backoff now re-arms only
        on stream PROGRESS, so accept-then-drop keeps the delays doubling
        and the dial count over a fixed window stays small."""
        import socket
        import threading
        import time as _t

        server = APIServer()
        httpd, _ = serve(RestAPI(server), 0)
        port = httpd.server_address[1]
        store = KubeStore(f"http://127.0.0.1:{port}", seed=3)
        w = store.watch(kinds=["CM"])
        try:
            server.create({"kind": "CM", "apiVersion": "v1",
                           "metadata": {"name": "c", "namespace": "d"},
                           "spec": {}})
            assert wait(lambda: w.next(timeout=1))  # stream progressed once
            httpd.shutdown()
            httpd.server_close()
            w._resp.close()

            # the flapper: same port, accepts and instantly drops
            lsock = socket.socket()
            lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            for _ in range(50):
                try:
                    lsock.bind(("127.0.0.1", port))
                    break
                except OSError:
                    _t.sleep(0.05)
            lsock.listen(64)
            lsock.settimeout(0.1)
            accepts = []
            stop = threading.Event()

            def flap():
                while not stop.is_set():
                    try:
                        conn, _ = lsock.accept()
                    except socket.timeout:
                        continue
                    except OSError:
                        return
                    accepts.append(_t.monotonic())
                    conn.close()

            t = threading.Thread(target=flap, daemon=True)
            t.start()
            try:
                _t.sleep(1.5)
                # worst case with backoff: delays >= 0.1, 0.2, 0.4, 0.8...
                # so ~5 dials fit in 1.5s; a hot spin lands hundreds
                assert 1 <= len(accepts) <= 10, len(accepts)
            finally:
                stop.set()
                t.join()
                lsock.close()
        finally:
            w.stop()
            store.close()


class TestFencingOverTheWire:
    def test_client_learns_epoch_and_stamps_writes(self):
        from kubeflow_tpu.core.store import FencedWrite

        server = APIServer()
        server.set_epoch(2)
        httpd, _ = serve(RestAPI(server), 0)
        store = KubeStore(f"http://127.0.0.1:{httpd.server_address[1]}")
        try:
            # first write is unstamped (client knows no epoch yet); the
            # response header teaches it the current fencing epoch
            store.create({"kind": "CM", "apiVersion": "v1",
                          "metadata": {"name": "a", "namespace": "d"},
                          "spec": {}})
            assert store.epoch == 2
            # stamped writes at the current epoch pass the gate
            store.create({"kind": "CM", "apiVersion": "v1",
                          "metadata": {"name": "b", "namespace": "d"},
                          "spec": {}})
            # leadership moves: the lease transfer bumps the epoch, and
            # the client's stale stamp now answers a TYPED 409
            server.set_epoch(3)
            with pytest.raises(FencedWrite) as ei:
                store.create({"kind": "CM", "apiVersion": "v1",
                              "metadata": {"name": "c", "namespace": "d"},
                              "spec": {}})
            assert ei.value.current_epoch == 3
            # ...which carried the new epoch: the retry succeeds
            assert store.epoch == 3
            store.create({"kind": "CM", "apiVersion": "v1",
                          "metadata": {"name": "c", "namespace": "d"},
                          "spec": {}})
            assert server.get("CM", "c", "d")
        finally:
            store.close()
            httpd.shutdown()

    def test_epoch_learning_is_monotonic(self):
        """A deposed leader still answering with its OLD epoch must not
        walk the client's learned epoch backwards — max-only learning is
        what stops a partitioned stale leader silently accepting writes
        the new timeline never sees."""
        server = APIServer()
        server.set_epoch(5)
        httpd, _ = serve(RestAPI(server), 0)
        store = KubeStore(f"http://127.0.0.1:{httpd.server_address[1]}")
        try:
            store.create({"kind": "CM", "apiVersion": "v1",
                          "metadata": {"name": "a", "namespace": "d"},
                          "spec": {}})
            assert store.epoch == 5
            store._note_epoch("3")  # stale header from a deposed leader
            assert store.epoch == 5
        finally:
            store.close()
            httpd.shutdown()
