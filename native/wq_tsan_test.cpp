// ThreadSanitizer harness for the native workqueue: producers add/backoff
// keys while consumers drain and a meddler polls depth/forgets — the
// access pattern the Manager's watch-dispatch + worker threads generate.
// Build & run: make tsan-run (CI gate; any data race fails the binary).

#include <atomic>
#include <cstdio>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* kf_wq_new();
void kf_wq_free(void* q);
void kf_wq_add(void* q, const char* key, double delay);
void kf_wq_add_rate_limited(void* q, const char* key);
void kf_wq_forget(void* q, const char* key);
int kf_wq_get(void* q, double timeout, char* out, int cap);
int kf_wq_depth(void* q);
int kf_wq_due_now(void* q, double horizon);
void kf_wq_shutdown(void* q);
}

int main() {
    void* q = kf_wq_new();
    std::atomic<int> got{0};
    std::atomic<int> producers_live{0};
    const int kProducers = 4, kConsumers = 4, kPerProducer = 250;

    std::vector<std::thread> threads;
    for (int p = 0; p < kProducers; p++) {
        producers_live.fetch_add(1);
        threads.emplace_back([q, p, &producers_live] {
            char key[64];
            for (int i = 0; i < kPerProducer; i++) {
                snprintf(key, sizeof key, "ns/%d-%d", p, i % 50);
                if (i % 3 == 0)
                    kf_wq_add_rate_limited(q, key);
                else
                    kf_wq_add(q, key, (i % 5) * 0.0002);
            }
            producers_live.fetch_sub(1);
        });
    }
    for (int c = 0; c < kConsumers; c++) {
        threads.emplace_back([q, &got, &producers_live] {
            char out[256];
            for (;;) {
                const int rc = kf_wq_get(q, 0.05, out, sizeof out);
                if (rc == -1) return;  // shutdown
                if (rc > 0) {
                    got.fetch_add(1);
                    kf_wq_forget(q, out);
                } else if (producers_live.load() == 0 &&
                           kf_wq_depth(q) == 0) {
                    return;  // producers finished and queue drained
                }
            }
        });
    }
    threads.emplace_back([q] {  // meddler
        for (int i = 0; i < 200; i++) {
            kf_wq_depth(q);
            kf_wq_due_now(q, 0.01);
        }
    });
    for (auto& t : threads) t.join();
    kf_wq_shutdown(q);
    char out[256];
    if (kf_wq_get(q, 0.01, out, sizeof out) != -1) {
        std::fprintf(stderr, "FAIL: get after shutdown != -1\n");
        return 1;
    }
    kf_wq_free(q);
    // dedup means got <= adds; it must still have drained a healthy number
    if (got.load() < 50) {
        std::fprintf(stderr, "FAIL: only %d keys drained\n", got.load());
        return 1;
    }
    std::printf("wq tsan ok: drained %d keys\n", got.load());
    return 0;
}
