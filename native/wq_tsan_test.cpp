// ThreadSanitizer harness for the native workqueue: producers add/backoff
// keys while a consumer POOL drains (get/process/done — the worker-pool
// protocol) and a meddler polls depth/in_flight — the access pattern the
// Manager's watch-dispatch + N pool workers generate.  Each consumer
// checks the client-go invariant: a key handed out by get() is never
// held by two workers at once (per-key in-flight flags), and a key
// re-added mid-processing reruns after done() instead of being lost.
// Build & run: make tsan-run (CI gate; any data race fails the binary).

#include <atomic>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* kf_wq_new();
void kf_wq_free(void* q);
void kf_wq_add(void* q, const char* key, double delay);
void kf_wq_add_rate_limited(void* q, const char* key);
void kf_wq_forget(void* q, const char* key);
int kf_wq_get(void* q, double timeout, char* out, int cap);
void kf_wq_done(void* q, const char* key);
int kf_wq_depth(void* q);
int kf_wq_in_flight(void* q);
int kf_wq_due_now(void* q, double horizon);
void kf_wq_shutdown(void* q);
}

namespace {
constexpr int kProducers = 4, kConsumers = 4, kPerProducer = 250;
constexpr int kKeySpace = 50;  // keys are ns/<p>-<i%50>

// per-key single-flight flags; index = p * kKeySpace + (i % kKeySpace)
std::atomic<int> in_flight_flag[kProducers * kKeySpace];

int key_index(const char* key) {
    int p = 0, i = 0;
    if (std::sscanf(key, "ns/%d-%d", &p, &i) != 2) return -1;
    return p * kKeySpace + i;
}
}  // namespace

int main() {
    void* q = kf_wq_new();
    std::atomic<int> got{0};
    std::atomic<bool> overlap{false};
    std::atomic<int> producers_live{0};

    std::vector<std::thread> threads;
    for (int p = 0; p < kProducers; p++) {
        producers_live.fetch_add(1);
        threads.emplace_back([q, p, &producers_live] {
            char key[64];
            for (int i = 0; i < kPerProducer; i++) {
                snprintf(key, sizeof key, "ns/%d-%d", p, i % kKeySpace);
                if (i % 3 == 0)
                    kf_wq_add_rate_limited(q, key);
                else
                    kf_wq_add(q, key, (i % 5) * 0.0002);
            }
            producers_live.fetch_sub(1);
        });
    }
    for (int c = 0; c < kConsumers; c++) {
        threads.emplace_back([q, &got, &overlap, &producers_live] {
            char out[256];
            for (;;) {
                const int rc = kf_wq_get(q, 0.05, out, sizeof out);
                if (rc == -1) return;  // shutdown
                if (rc > 0) {
                    const int idx = key_index(out);
                    if (idx >= 0 &&
                        in_flight_flag[idx].exchange(1) != 0)
                        overlap.store(true);  // handed out twice!
                    got.fetch_add(1);
                    // re-add mid-processing: must park dirty, not dup
                    if (got.load() % 7 == 0) kf_wq_add(q, out, 0.0);
                    kf_wq_forget(q, out);
                    if (idx >= 0) in_flight_flag[idx].store(0);
                    kf_wq_done(q, out);
                } else if (producers_live.load() == 0 &&
                           kf_wq_depth(q) == 0 &&
                           kf_wq_in_flight(q) == 0) {
                    return;  // producers finished, drained, nothing held
                }
            }
        });
    }
    threads.emplace_back([q] {  // meddler
        for (int i = 0; i < 200; i++) {
            kf_wq_depth(q);
            kf_wq_in_flight(q);
            kf_wq_due_now(q, 0.01);
        }
    });
    for (auto& t : threads) t.join();
    kf_wq_shutdown(q);
    char out[256];
    if (kf_wq_get(q, 0.01, out, sizeof out) != -1) {
        std::fprintf(stderr, "FAIL: get after shutdown != -1\n");
        return 1;
    }
    if (overlap.load()) {
        std::fprintf(stderr, "FAIL: a key was handed to two workers\n");
        return 1;
    }
    if (kf_wq_in_flight(q) != 0) {
        std::fprintf(stderr, "FAIL: in_flight != 0 after drain\n");
        return 1;
    }
    kf_wq_free(q);
    // dedup means got <= adds; it must still have drained a healthy number
    if (got.load() < 50) {
        std::fprintf(stderr, "FAIL: only %d keys drained\n", got.load());
        return 1;
    }

    // single-threaded semantics check for the dirty path: a key re-added
    // while processing runs exactly once more after done()
    void* q2 = kf_wq_new();
    kf_wq_add(q2, "ns/again", 0.0);
    char buf[64];
    if (kf_wq_get(q2, 0.5, buf, sizeof buf) <= 0 ||
        std::strcmp(buf, "ns/again") != 0) {
        std::fprintf(stderr, "FAIL: dirty-path get #1\n");
        return 1;
    }
    kf_wq_add(q2, "ns/again", 0.0);  // while processing -> dirty
    if (kf_wq_get(q2, 0.02, buf, sizeof buf) != 0) {
        std::fprintf(stderr, "FAIL: processing key handed out again\n");
        return 1;
    }
    kf_wq_done(q2, "ns/again");
    if (kf_wq_get(q2, 0.5, buf, sizeof buf) <= 0) {
        std::fprintf(stderr, "FAIL: dirty re-add lost after done\n");
        return 1;
    }
    kf_wq_done(q2, "ns/again");
    if (kf_wq_get(q2, 0.02, buf, sizeof buf) != 0) {
        std::fprintf(stderr, "FAIL: dirty re-add ran more than once\n");
        return 1;
    }
    // oversized-key path: a key the caller's buffer can't hold must be
    // ABANDONED (processing cleared, dirty dropped), not wedged in flight
    kf_wq_add(q2, "ns/a-name-far-longer-than-the-tiny-buffer", 0.0);
    char tiny[4];
    if (kf_wq_get(q2, 0.5, tiny, sizeof tiny) != -2) {
        std::fprintf(stderr, "FAIL: oversized key should return -2\n");
        return 1;
    }
    if (kf_wq_in_flight(q2) != 0 || kf_wq_depth(q2) != 0) {
        std::fprintf(stderr, "FAIL: oversized key wedged in flight\n");
        return 1;
    }
    kf_wq_shutdown(q2);
    kf_wq_free(q2);

    std::printf("wq tsan ok: drained %d keys, no double-dispatch\n",
                got.load());
    return 0;
}
