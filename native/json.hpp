// Minimal JSON value / parser / serializer for the native engine.
// Self-contained (no external deps are available in this environment).
// Supports the full JSON grammar; numbers are stored as double plus an
// integer flag so round-trips of counts/ports stay integral.
#pragma once

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace kjson {

class Value;
using Object = std::map<std::string, Value>;
using Array = std::vector<Value>;

class Value {
 public:
  enum class Type { Null, Bool, Int, Double, String, Array, Object };

  Value() : type_(Type::Null) {}
  Value(bool b) : type_(Type::Bool), bool_(b) {}
  Value(int64_t i) : type_(Type::Int), int_(i) {}
  Value(double d) : type_(Type::Double), double_(d) {}
  Value(const std::string& s) : type_(Type::String), str_(s) {}
  Value(const char* s) : type_(Type::String), str_(s) {}
  Value(Array a) : type_(Type::Array), arr_(std::move(a)) {}
  Value(Object o) : type_(Type::Object), obj_(std::move(o)) {}

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_object() const { return type_ == Type::Object; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_string() const { return type_ == Type::String; }

  bool as_bool() const { return bool_; }
  int64_t as_int() const {
    return type_ == Type::Int ? int_ : static_cast<int64_t>(double_);
  }
  double as_double() const {
    return type_ == Type::Double ? double_ : static_cast<double>(int_);
  }
  const std::string& as_string() const { return str_; }

  Array& arr() { return arr_; }
  const Array& arr() const { return arr_; }
  Object& obj() { return obj_; }
  const Object& obj() const { return obj_; }

  bool has(const std::string& k) const {
    return type_ == Type::Object && obj_.count(k) > 0;
  }
  const Value& at(const std::string& k) const {
    static Value null_value;
    auto it = obj_.find(k);
    return it == obj_.end() ? null_value : it->second;
  }
  Value& operator[](const std::string& k) {
    if (type_ == Type::Null) type_ = Type::Object;
    return obj_[k];
  }

  bool operator==(const Value& o) const {
    if (type_ != o.type_) {
      // ints and doubles compare numerically
      if ((type_ == Type::Int && o.type_ == Type::Double) ||
          (type_ == Type::Double && o.type_ == Type::Int))
        return as_double() == o.as_double();
      return false;
    }
    switch (type_) {
      case Type::Null: return true;
      case Type::Bool: return bool_ == o.bool_;
      case Type::Int: return int_ == o.int_;
      case Type::Double: return double_ == o.double_;
      case Type::String: return str_ == o.str_;
      case Type::Array: return arr_ == o.arr_;
      case Type::Object: return obj_ == o.obj_;
    }
    return false;
  }
  bool operator!=(const Value& o) const { return !(*this == o); }

  std::string dump() const {
    std::ostringstream os;
    write(os);
    return os.str();
  }

 private:
  void write(std::ostringstream& os) const {
    switch (type_) {
      case Type::Null: os << "null"; break;
      case Type::Bool: os << (bool_ ? "true" : "false"); break;
      case Type::Int: os << int_; break;
      case Type::Double: {
        if (std::isfinite(double_)) {
          std::ostringstream tmp;
          tmp.precision(17);
          tmp << double_;
          os << tmp.str();
        } else {
          os << "null";
        }
        break;
      }
      case Type::String: write_string(os, str_); break;
      case Type::Array: {
        os << '[';
        for (size_t i = 0; i < arr_.size(); ++i) {
          if (i) os << ',';
          arr_[i].write(os);
        }
        os << ']';
        break;
      }
      case Type::Object: {
        os << '{';
        bool first = true;
        for (const auto& kv : obj_) {
          if (!first) os << ',';
          first = false;
          write_string(os, kv.first);
          os << ':';
          kv.second.write(os);
        }
        os << '}';
        break;
      }
    }
  }

  static void write_string(std::ostringstream& os, const std::string& s) {
    os << '"';
    for (unsigned char c : s) {
      switch (c) {
        case '"': os << "\\\""; break;
        case '\\': os << "\\\\"; break;
        case '\b': os << "\\b"; break;
        case '\f': os << "\\f"; break;
        case '\n': os << "\\n"; break;
        case '\r': os << "\\r"; break;
        case '\t': os << "\\t"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof buf, "\\u%04x", c);
            os << buf;
          } else {
            os << c;
          }
      }
    }
    os << '"';
  }

  Type type_;
  bool bool_ = false;
  int64_t int_ = 0;
  double double_ = 0;
  std::string str_;
  Array arr_;
  Object obj_;
};

Value number_from(const std::string& s, size_t& pos);

class Parser {
 public:
  explicit Parser(const std::string& text) : s_(text) {}

  Value parse() {
    Value v = value();
    skip_ws();
    if (pos_ != s_.size()) throw std::runtime_error("trailing JSON data");
    return v;
  }

 private:
  void skip_ws() {
    while (pos_ < s_.size() &&
           (s_[pos_] == ' ' || s_[pos_] == '\t' || s_[pos_] == '\n' ||
            s_[pos_] == '\r'))
      ++pos_;
  }
  char peek() {
    skip_ws();
    if (pos_ >= s_.size()) throw std::runtime_error("unexpected end of JSON");
    return s_[pos_];
  }
  void expect(char c) {
    if (peek() != c)
      throw std::runtime_error(std::string("expected '") + c + "'");
    ++pos_;
  }

  Value value() {
    switch (peek()) {
      case '{': return object();
      case '[': return array();
      case '"': return Value(string());
      case 't': literal("true"); return Value(true);
      case 'f': literal("false"); return Value(false);
      case 'n': literal("null"); return Value();
      default: return number();
    }
  }

  void literal(const char* lit) {
    skip_ws();
    size_t n = strlen(lit);
    if (s_.compare(pos_, n, lit) != 0)
      throw std::runtime_error("invalid JSON literal");
    pos_ += n;
  }

  Value object() {
    expect('{');
    Object o;
    if (peek() == '}') { ++pos_; return Value(std::move(o)); }
    while (true) {
      std::string key = string();
      expect(':');
      o[key] = value();
      char c = peek();
      ++pos_;
      if (c == '}') break;
      if (c != ',') throw std::runtime_error("expected ',' in object");
    }
    return Value(std::move(o));
  }

  Value number() {
    skip_ws();
    return number_from(s_, pos_);
  }

  Value array() {
    expect('[');
    Array a;
    if (peek() == ']') { ++pos_; return Value(std::move(a)); }
    while (true) {
      a.push_back(value());
      char c = peek();
      ++pos_;
      if (c == ']') break;
      if (c != ',') throw std::runtime_error("expected ',' in array");
    }
    return Value(std::move(a));
  }

  std::string string() {
    skip_ws();
    if (s_[pos_] != '"') throw std::runtime_error("expected string");
    ++pos_;
    std::string out;
    while (pos_ < s_.size()) {
      char c = s_[pos_++];
      if (c == '"') return out;
      if (c == '\\') {
        if (pos_ >= s_.size()) break;
        char e = s_[pos_++];
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            if (pos_ + 4 > s_.size())
              throw std::runtime_error("bad \\u escape");
            unsigned cp = std::stoul(s_.substr(pos_, 4), nullptr, 16);
            pos_ += 4;
            // surrogate pair
            if (cp >= 0xD800 && cp <= 0xDBFF && pos_ + 6 <= s_.size() &&
                s_[pos_] == '\\' && s_[pos_ + 1] == 'u') {
              unsigned lo = std::stoul(s_.substr(pos_ + 2, 4), nullptr, 16);
              if (lo >= 0xDC00 && lo <= 0xDFFF) {
                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                pos_ += 6;
              }
            }
            append_utf8(out, cp);
            break;
          }
          default: throw std::runtime_error("bad escape");
        }
      } else {
        out += c;
      }
    }
    throw std::runtime_error("unterminated string");
  }

  static void append_utf8(std::string& out, unsigned cp) {
    if (cp < 0x80) {
      out += static_cast<char>(cp);
    } else if (cp < 0x800) {
      out += static_cast<char>(0xC0 | (cp >> 6));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      out += static_cast<char>(0xE0 | (cp >> 12));
      out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    } else {
      out += static_cast<char>(0xF0 | (cp >> 18));
      out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
      out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    }
  }

  const std::string& s_;
  size_t pos_ = 0;
};

inline Value parse(const std::string& text) { return Parser(text).parse(); }

// Scans exactly the JSON number grammar: -?(0|[1-9][0-9]*)(\.[0-9]+)?
// ([eE][+-]?[0-9]+)?.  Leading '+', interior signs, and "1." / ".5" style
// tokens are rejected; any trailing garbage is left at pos for the caller
// to choke on.
inline Value number_from(const std::string& s, size_t& pos) {
  size_t start = pos;
  bool is_int = true;
  auto digit = [&](size_t p) {
    return p < s.size() && isdigit(static_cast<unsigned char>(s[p]));
  };
  if (pos < s.size() && s[pos] == '-') ++pos;
  if (!digit(pos)) throw std::runtime_error("invalid JSON number");
  if (s[pos] == '0') {
    ++pos;  // leading zeros are not numbers; a following digit is garbage
  } else {
    while (digit(pos)) ++pos;
  }
  if (pos < s.size() && s[pos] == '.') {
    is_int = false;
    ++pos;
    if (!digit(pos)) throw std::runtime_error("invalid JSON number");
    while (digit(pos)) ++pos;
  }
  if (pos < s.size() && (s[pos] == 'e' || s[pos] == 'E')) {
    is_int = false;
    ++pos;
    if (pos < s.size() && (s[pos] == '+' || s[pos] == '-')) ++pos;
    if (!digit(pos)) throw std::runtime_error("invalid JSON number");
    while (digit(pos)) ++pos;
  }
  std::string tok = s.substr(start, pos - start);
  if (is_int) {
    try {
      return Value(static_cast<int64_t>(std::stoll(tok)));
    } catch (...) {  // out of int64 range: fall through to double
    }
  }
  return Value(std::stod(tok));
}

}  // namespace kjson
