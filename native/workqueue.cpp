// Native workqueue: the controller runtime's hot data structure.
//
// The reference's controller-runtime (Go) implements this as the
// rate-limited delaying workqueue under every reconciler; here it is C++
// behind a C ABI, driven from Python worker threads via ctypes (which
// releases the GIL for the blocking get, so a parked worker costs nothing).
//
// Semantics (mirrors kubeflow_tpu/core/controller.py WorkQueue exactly):
//  - add(key, delay): dedup — keep only the EARLIEST scheduled run per key;
//    later duplicates are no-ops, earlier ones supersede (stale heap entries
//    are skipped on pop).
//  - add_rate_limited(key): per-key exponential failure backoff
//    5ms * 2^n capped at 30s; forget(key) resets.
//  - get(timeout): blocks until a key is due, the timeout lapses (returns
//    0) or shutdown (returns -1).
//  - client-go processing/dirty protocol (workqueue.Type): a key handed
//    out by get() moves to the PROCESSING set and is never handed to a
//    second caller; add() of a processing key parks it in the DIRTY map
//    (earliest requested run time wins) and done(key) republishes it, so
//    a key re-added mid-reconcile runs exactly once more — never lost,
//    never run concurrently with itself.

#include <chrono>
#include <condition_variable>
#include <cstring>
#include <mutex>
#include <queue>
#include <string>
#include <tuple>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

double now_s() {
    return std::chrono::duration<double>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
}

constexpr double kBaseDelay = 0.005;
constexpr double kMaxDelay = 30.0;

struct Entry {
    double when;
    unsigned long long seq;
    std::string key;
    bool operator>(const Entry& o) const {
        return std::tie(when, seq) > std::tie(o.when, o.seq);
    }
};

class WorkQueue {
  public:
    void add(const std::string& key, double delay) {
        const double when = now_s() + delay;
        std::lock_guard<std::mutex> g(mu_);
        if (processing_.count(key)) {
            auto it = dirty_.find(key);
            if (it == dirty_.end() || when < it->second) dirty_[key] = when;
            return;
        }
        auto it = due_.find(key);
        if (it != due_.end() && it->second <= when) return;
        due_[key] = when;
        heap_.push(Entry{when, ++seq_, key});
        // one key became runnable: wake ONE worker (notify_all stampeded
        // every parked pool worker per add; get() re-arms the chain)
        cv_.notify_one();
    }

    void add_rate_limited(const std::string& key) {
        int n;
        {
            std::lock_guard<std::mutex> g(mu_);
            n = failures_[key]++;
        }
        double delay = kBaseDelay;
        for (int i = 0; i < n && delay < kMaxDelay; i++) delay *= 2;
        if (delay > kMaxDelay) delay = kMaxDelay;
        add(key, delay);
    }

    void forget(const std::string& key) {
        std::lock_guard<std::mutex> g(mu_);
        failures_.erase(key);
    }

    // 1 = key written to *out; 0 = timeout; -1 = shutdown
    int get(double timeout, std::string* out) {
        std::unique_lock<std::mutex> lk(mu_);
        const double deadline = now_s() + timeout;
        while (!shutdown_) {
            const double now = now_s();
            while (!heap_.empty() && heap_.top().when <= now) {
                Entry e = heap_.top();
                heap_.pop();
                auto it = due_.find(e.key);
                if (it == due_.end() || it->second != e.when)
                    continue;  // superseded by an earlier reschedule
                due_.erase(it);
                processing_.insert(e.key);
                // cascade: more work due now -> wake the next worker
                // (each add only notified one)
                if (!heap_.empty() && heap_.top().when <= now)
                    cv_.notify_one();
                *out = std::move(e.key);
                return 1;
            }
            double wait = deadline - now;
            if (!heap_.empty()) {
                const double until_due = heap_.top().when - now;
                if (until_due < wait) wait = until_due;
            }
            if (wait <= 0) return 0;
            cv_.wait_for(lk, std::chrono::duration<double>(wait));
        }
        return -1;
    }

    // drop a key the caller could not receive (kf_wq_get's too-small
    // buffer): clear processing AND any dirty re-add, restoring the
    // pre-pool semantics "dropped once, recoverable by a future add" —
    // running done() instead would republish the same oversized key in
    // a hot -2 loop, and doing nothing would wedge it in processing_
    // (in_flight never drains, every re-add parks dirty forever)
    void abandon(const std::string& key) {
        std::lock_guard<std::mutex> g(mu_);
        processing_.erase(key);
        dirty_.erase(key);
    }

    // worker finished the key: republish a dirty re-add (at its earliest
    // requested run time) so the mid-reconcile event is not lost
    void done(const std::string& key) {
        std::lock_guard<std::mutex> g(mu_);
        if (!processing_.erase(key)) return;
        auto it = dirty_.find(key);
        if (it == dirty_.end()) return;
        const double when = it->second;
        dirty_.erase(it);
        due_[key] = when;
        heap_.push(Entry{when, ++seq_, key});
        cv_.notify_one();
    }

    int depth() {
        std::lock_guard<std::mutex> g(mu_);
        return static_cast<int>(due_.size() + dirty_.size());
    }

    int in_flight() {
        std::lock_guard<std::mutex> g(mu_);
        return static_cast<int>(processing_.size());
    }

    int due_now(double horizon) {
        const double cutoff = now_s() + horizon;
        std::lock_guard<std::mutex> g(mu_);
        int n = 0;
        for (const auto& kv : due_)
            if (kv.second <= cutoff) n++;
        for (const auto& kv : dirty_)  // reruns as soon as done() lands
            if (kv.second <= cutoff) n++;
        return n;
    }

    void shutdown() {
        std::lock_guard<std::mutex> g(mu_);
        shutdown_ = true;
        cv_.notify_all();
    }

  private:
    std::mutex mu_;
    std::condition_variable cv_;
    std::priority_queue<Entry, std::vector<Entry>, std::greater<Entry>> heap_;
    std::unordered_map<std::string, double> due_;
    std::unordered_set<std::string> processing_;
    std::unordered_map<std::string, double> dirty_;
    std::unordered_map<std::string, int> failures_;
    unsigned long long seq_ = 0;
    bool shutdown_ = false;
};

}  // namespace

extern "C" {

void* kf_wq_new() { return new WorkQueue(); }

void kf_wq_free(void* q) { delete static_cast<WorkQueue*>(q); }

void kf_wq_add(void* q, const char* key, double delay) {
    static_cast<WorkQueue*>(q)->add(key, delay);
}

void kf_wq_add_rate_limited(void* q, const char* key) {
    static_cast<WorkQueue*>(q)->add_rate_limited(key);
}

void kf_wq_forget(void* q, const char* key) {
    static_cast<WorkQueue*>(q)->forget(key);
}

// >0: length of key copied into out (NUL-terminated); 0: timeout;
// -1: shutdown; -2: out buffer too small (key stays consumed — size the
// buffer generously, keys are "<ns>/<name>")
int kf_wq_get(void* q, double timeout, char* out, int cap) {
    std::string key;
    const int rc = static_cast<WorkQueue*>(q)->get(timeout, &key);
    if (rc != 1) return rc;
    if (static_cast<int>(key.size()) + 1 > cap) {
        // undeliverable: release it or it wedges in the processing set
        static_cast<WorkQueue*>(q)->abandon(key);
        return -2;
    }
    std::memcpy(out, key.c_str(), key.size() + 1);
    return static_cast<int>(key.size());
}

void kf_wq_done(void* q, const char* key) {
    static_cast<WorkQueue*>(q)->done(key);
}

int kf_wq_depth(void* q) { return static_cast<WorkQueue*>(q)->depth(); }

int kf_wq_in_flight(void* q) {
    return static_cast<WorkQueue*>(q)->in_flight();
}

int kf_wq_due_now(void* q, double horizon) {
    return static_cast<WorkQueue*>(q)->due_now(horizon);
}

void kf_wq_shutdown(void* q) { static_cast<WorkQueue*>(q)->shutdown(); }

}  // extern "C"
