// kfengine: the platform's native reconcile/admission engine.
//
// Compiled-language equivalents of the reference's Go hot paths:
//  - PodDefault admission merge with exact conflict semantics
//    (reference: components/admission-webhook/main.go:98-424 — env merged by
//    name with value-equality conflicts, envFrom append-only, volumeMounts
//    keyed by name AND mountPath, volumes by name, tolerations by key,
//    annotations/labels maps with per-key equality);
//  - create-or-update field copy for reconciled children
//    (reference: components/common/reconcilehelper/util.go — copy desired
//    spec/labels into the live object, report whether anything changed);
//  - label-selector matching (matchLabels + matchExpressions).
//
// C ABI: every function takes JSON strings and returns a malloc'd JSON
// string {"ok": ..., "error": ...}; caller frees via kf_free.

#include <cstring>
#include <string>

#include "json.hpp"

using kjson::Array;
using kjson::Object;
using kjson::Value;

namespace {

char* dup_result(const Value& v) {
  std::string s = v.dump();
  char* out = static_cast<char*>(malloc(s.size() + 1));
  memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

char* ok_result(Value payload) {
  Object o;
  o["ok"] = std::move(payload);
  return dup_result(Value(std::move(o)));
}

char* err_result(const std::string& message) {
  Object o;
  o["error"] = Value(message);
  return dup_result(Value(std::move(o)));
}

// ---------------------------------------------------------------------------
// label selector
// ---------------------------------------------------------------------------

bool contains(const Array& values, const std::string& v) {
  for (const auto& x : values)
    if (x.is_string() && x.as_string() == v) return true;
  return false;
}

bool match_selector(const Value& selector, const Value& labels) {
  if (selector.is_null() ||
      (selector.is_object() && selector.obj().empty()))
    return true;
  const Value& ml = selector.at("matchLabels");
  if (ml.is_object()) {
    for (const auto& kv : ml.obj()) {
      if (!labels.is_object() || labels.at(kv.first) != kv.second)
        return false;
    }
  }
  const Value& mes = selector.at("matchExpressions");
  if (mes.is_array()) {
    for (const auto& expr : mes.arr()) {
      std::string key = expr.at("key").as_string();
      std::string op = expr.at("operator").as_string();
      bool has = labels.is_object() && labels.has(key);
      std::string val = has ? labels.at(key).as_string() : "";
      const Value& values = expr.at("values");
      Array empty;
      const Array& vals = values.is_array() ? values.arr() : empty;
      if (op == "In") {
        if (!has || !contains(vals, val)) return false;
      } else if (op == "NotIn") {
        if (has && contains(vals, val)) return false;
      } else if (op == "Exists") {
        if (!has) return false;
      } else if (op == "DoesNotExist") {
        if (has) return false;
      } else {
        throw std::runtime_error("unknown selector operator: " + op);
      }
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// PodDefault merge (admission-webhook main.go semantics)
// ---------------------------------------------------------------------------

// merge list items keyed by key fields; equal duplicates pass, unequal
// duplicates conflict.  Returns merged list or throws.
Array merge_keyed(const Array& existing, const Array& added,
                  const std::vector<std::string>& key_fields,
                  const std::string& what) {
  Array out = existing;
  for (const auto& item : added) {
    bool dup = false;
    for (const auto& have : out) {
      bool same_key = true;
      for (const auto& kf : key_fields) {
        if (have.at(kf) != item.at(kf)) {
          same_key = false;
          break;
        }
      }
      if (same_key) {
        if (have != item)
          throw std::runtime_error(
              "conflict on " + what + " " +
              item.at(key_fields[0]).as_string());
        dup = true;
        break;
      }
    }
    if (!dup) out.push_back(item);
  }
  return out;
}

Object merge_maps(const Value& existing, const Value& added,
                  const std::string& what) {
  Object out = existing.is_object() ? existing.obj() : Object{};
  if (added.is_object()) {
    for (const auto& kv : added.obj()) {
      auto it = out.find(kv.first);
      if (it != out.end() && it->second != kv.second)
        throw std::runtime_error("conflict on " + what + " key " + kv.first);
      out[kv.first] = kv.second;
    }
  }
  return out;
}

Value get_path(const Value& v, std::initializer_list<const char*> path) {
  const Value* cur = &v;
  for (const char* p : path) {
    if (!cur->is_object()) return Value();
    cur = &cur->at(p);
  }
  return *cur;
}

// apply a list of PodDefaults to a pod; throws on conflict.
Value apply_poddefaults(Value pod, const Array& poddefaults) {
  Value& spec = pod["spec"];
  Object& meta = pod["metadata"].obj();

  // annotations/labels across all poddefaults and the pod
  Value ann = meta.count("annotations") ? meta["annotations"] : Value(Object{});
  Value lab = meta.count("labels") ? meta["labels"] : Value(Object{});
  Value volumes = spec.has("volumes") ? spec.at("volumes") : Value(Array{});
  Value tolerations =
      spec.has("tolerations") ? spec.at("tolerations") : Value(Array{});

  Array applied_names;
  for (const auto& pd : poddefaults) {
    const Value& pdspec = pd.at("spec");
    ann = Value(merge_maps(ann, pdspec.at("annotations"), "annotation"));
    lab = Value(merge_maps(lab, pdspec.at("labels"), "label"));
    if (pdspec.at("volumes").is_array())
      volumes = Value(merge_keyed(volumes.arr(), pdspec.at("volumes").arr(),
                                  {"name"}, "volume"));
    if (pdspec.at("tolerations").is_array())
      tolerations =
          Value(merge_keyed(tolerations.arr(), pdspec.at("tolerations").arr(),
                            {"key"}, "toleration"));
    applied_names.push_back(pd.at("metadata").at("name"));
    // record application annotation: poddefault.admission.kubeflow.org/
    // poddefault-<name> = resourceVersion (main.go:416-419)
    std::string akey = "poddefault.admission.kubeflow-tpu.org/poddefault-" +
                       pd.at("metadata").at("name").as_string();
    Value rv = get_path(pd, {"metadata", "resourceVersion"});
    Object annobj = ann.obj();
    annobj[akey] = rv.is_null() ? Value("0") : rv;
    ann = Value(std::move(annobj));
  }

  // containers: env (keyed by name, value-equality), envFrom (append),
  // volumeMounts (keyed by name AND mountPath)
  if (spec.has("containers") && spec.at("containers").is_array()) {
    Array containers = spec.at("containers").arr();
    for (auto& c : containers) {
      Value env = c.has("env") ? c.at("env") : Value(Array{});
      Value envFrom = c.has("envFrom") ? c.at("envFrom") : Value(Array{});
      Value mounts =
          c.has("volumeMounts") ? c.at("volumeMounts") : Value(Array{});
      for (const auto& pd : poddefaults) {
        const Value& pdspec = pd.at("spec");
        if (pdspec.at("env").is_array())
          env = Value(
              merge_keyed(env.arr(), pdspec.at("env").arr(), {"name"}, "env"));
        if (pdspec.at("envFrom").is_array())
          for (const auto& ef : pdspec.at("envFrom").arr())
            envFrom.arr().push_back(ef);
        if (pdspec.at("volumeMounts").is_array())
          mounts = Value(merge_keyed(mounts.arr(),
                                     pdspec.at("volumeMounts").arr(),
                                     {"name", "mountPath"}, "volumeMount"));
      }
      c["env"] = env;
      c["envFrom"] = envFrom;
      c["volumeMounts"] = mounts;
    }
    spec["containers"] = Value(std::move(containers));
  }

  spec["volumes"] = volumes;
  spec["tolerations"] = tolerations;
  pod["metadata"]["annotations"] = ann;
  pod["metadata"]["labels"] = lab;

  Object result;
  result["pod"] = pod;
  result["applied"] = Value(std::move(applied_names));
  return Value(std::move(result));
}

// ---------------------------------------------------------------------------
// reconcile field copy (common/reconcilehelper/util.go semantics)
// ---------------------------------------------------------------------------

Value reconcile_merge(Value live, const Value& desired) {
  bool changed = false;
  // metadata labels/annotations
  Value live_meta = live.at("metadata");
  const Value& want_meta = desired.at("metadata");
  for (const char* key : {"labels", "annotations"}) {
    if (!want_meta.at(key).is_null() &&
        live_meta.at(key) != want_meta.at(key)) {
      live["metadata"][key] = want_meta.at(key);
      changed = true;
    }
  }
  // spec: field-by-field copy (preserves fields the server set that the
  // desired object omits — e.g. clusterIP on Services)
  if (desired.at("spec").is_object()) {
    for (const auto& kv : desired.at("spec").obj()) {
      Value& live_spec = live["spec"];
      if (live_spec.at(kv.first) != kv.second) {
        live_spec[kv.first] = kv.second;
        changed = true;
      }
    }
  }
  Object result;
  result["object"] = live;
  result["changed"] = Value(changed);
  return Value(std::move(result));
}

}  // namespace

extern "C" {

void kf_free(char* p) { free(p); }

const char* kf_version() { return "kfengine/0.1.0"; }

// pod_json: Pod object; poddefaults_json: JSON array of PodDefault objects
// (caller pre-filters by label selector or leaves that to us via
// kf_filter_poddefaults).
char* kf_apply_poddefaults(const char* pod_json,
                           const char* poddefaults_json) {
  try {
    Value pod = kjson::parse(pod_json);
    Value pds = kjson::parse(poddefaults_json);
    if (!pds.is_array()) return err_result("poddefaults must be an array");
    return ok_result(apply_poddefaults(std::move(pod), pds.arr()));
  } catch (const std::exception& e) {
    return err_result(e.what());
  }
}

// returns the sub-array of poddefaults whose spec.selector matches the pod's
// labels (admission-webhook main.go:69-94)
char* kf_filter_poddefaults(const char* pod_json,
                            const char* poddefaults_json) {
  try {
    Value pod = kjson::parse(pod_json);
    Value pds = kjson::parse(poddefaults_json);
    Value labels = get_path(pod, {"metadata", "labels"});
    Array out;
    for (const auto& pd : pds.arr()) {
      if (match_selector(get_path(pd, {"spec", "selector"}), labels))
        out.push_back(pd);
    }
    return ok_result(Value(std::move(out)));
  } catch (const std::exception& e) {
    return err_result(e.what());
  }
}

char* kf_match_selector(const char* selector_json, const char* labels_json) {
  try {
    Value sel = kjson::parse(selector_json);
    Value labels = kjson::parse(labels_json);
    return ok_result(Value(match_selector(sel, labels)));
  } catch (const std::exception& e) {
    return err_result(e.what());
  }
}

char* kf_reconcile_merge(const char* live_json, const char* desired_json) {
  try {
    Value live = kjson::parse(live_json);
    Value desired = kjson::parse(desired_json);
    return ok_result(reconcile_merge(std::move(live), desired));
  } catch (const std::exception& e) {
    return err_result(e.what());
  }
}

}  // extern "C"
