// ThreadSanitizer harness for the native engine (SURVEY.md §5.2: the
// reference configures no race detection at all; the engine here is called
// concurrently from every controller worker thread plus the admission path,
// so its C API must be stateless/thread-safe).  Build + run via
// `make tsan-run`; any data race makes TSan exit non-zero.
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
char* kf_apply_poddefaults(const char* pod_json, const char* pds_json);
char* kf_filter_poddefaults(const char* pod_json, const char* pds_json);
char* kf_match_selector(const char* selector_json, const char* labels_json);
char* kf_reconcile_merge(const char* live_json, const char* desired_json);
void kf_free(char* p);
const char* kf_version();
}

static const char* POD =
    "{\"kind\":\"Pod\",\"metadata\":{\"name\":\"p\",\"labels\":"
    "{\"app\":\"nb\",\"team\":\"ml\"}},\"spec\":{\"containers\":"
    "[{\"name\":\"main\",\"env\":[{\"name\":\"A\",\"value\":\"1\"}]}]}}";
static const char* PDS =
    "[{\"kind\":\"PodDefault\",\"metadata\":{\"name\":\"tpu-env\","
    "\"resourceVersion\":\"7\"},\"spec\":{\"selector\":{\"matchLabels\":"
    "{\"app\":\"nb\"}},\"env\":[{\"name\":\"TPU\",\"value\":\"v5e\"}],"
    "\"tolerations\":[{\"key\":\"tpu\",\"operator\":\"Exists\"}]}}]";
static const char* LIVE =
    "{\"kind\":\"Service\",\"metadata\":{\"name\":\"s\"},\"spec\":"
    "{\"clusterIP\":\"10.0.0.1\",\"ports\":[{\"port\":80}]}}";
static const char* DESIRED =
    "{\"kind\":\"Service\",\"metadata\":{\"name\":\"s\"},\"spec\":"
    "{\"ports\":[{\"port\":80,\"targetPort\":8888}],\"selector\":"
    "{\"app\":\"nb\"}}}";

static bool has_error(const char* out) {
  return out == nullptr || std::strstr(out, "\"error\"") != nullptr;
}

int main() {
  const int kThreads = 8;
  const int kIters = 500;
  std::vector<std::thread> threads;
  std::vector<int> failures(kThreads, 0);
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([t, &failures] {
      for (int i = 0; i < kIters; ++i) {
        char* a = kf_apply_poddefaults(POD, PDS);
        char* f = kf_filter_poddefaults(POD, PDS);
        char* m = kf_match_selector("{\"matchLabels\":{\"app\":\"nb\"}}",
                                    "{\"app\":\"nb\"}");
        char* r = kf_reconcile_merge(LIVE, DESIRED);
        if (has_error(a) || has_error(f) || has_error(m) || has_error(r)) {
          failures[t]++;
        }
        kf_free(a);
        kf_free(f);
        kf_free(m);
        kf_free(r);
      }
    });
  }
  for (auto& th : threads) th.join();
  int total = 0;
  for (int f : failures) total += f;
  if (total) {
    std::fprintf(stderr, "engine returned errors under concurrency: %d\n",
                 total);
    return 1;
  }
  std::printf("tsan harness OK: %d threads x %d iters on %s\n", kThreads,
              kIters, kf_version());
  return 0;
}
